"""Fleet load twin: deterministic fleet-scale traffic against stub replicas.

Scheduler and autoscaler changes are *fleet* behaviors — priority
inversion shows up at 10 replicas under a burst, not in a unit test — but
a 10-50-replica fleet of real engines needs a TPU pod. This module is the
twin: **stub engine replicas** that serve the REAL serving-tier surface
(the gateway proxies to them, the FleetScraper scrapes them, the router
learns affinity over them, the autoscaler drains them) and run the REAL
scheduling policy (server/scheduler.py `SloScheduler` — the same object
the live Batcher drives), with the engine itself replaced by deterministic
simulated service times. The control plane under test is 100% the
production code; only the matmuls are fake.

Pieces:

* :class:`StubEngineReplica` — an HTTP replica emulating `server/api.py`'s
  wire surface: SSE ``/v1/chat/completions`` (class-aware admission,
  priority slots, preemption, prefix-cache hit simulation keyed on the
  router's OWN chain hashes), ``/metrics`` in the exact families the
  FleetScraper lifts, ``/stats``, ``/health``, ``/debug/hot_prefixes``,
  ``/debug/config``;
* :func:`make_mixed_trace` — a seeded scenario-trace generator (the
  `server/chaos.py` FaultPlan idiom: one `random.Random(seed)` stream,
  identical replay per seed) mixing chat bursts, shared-prefix RAG
  fan-out, agentic tool loops with long pauses, batch jobs, and client
  abandonment;
* :class:`LoadTwin` — N stub replicas behind a REAL gateway (balancer +
  router + fleet scraper + optional autoscaler), a trace replayer whose
  clients measure TTFT at the first SSE byte, and a per-class report.

CI-cheap by construction: everything is host-side sleeps of a few ms —
a 10-replica mixed trace runs in seconds on one core, no jax imported.
"""

from __future__ import annotations

import http.client
import json
import random
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..runtime.tracing import prom_line as _prom  # stdlib-only: one
# Prometheus line formatter (escaping included) for the whole serving
# layer — the twin must emit exactly what the scraper parses
from .quarantine import QuarantineLedger, request_fingerprint
from .router import PAGE_CHARS, messages_prefix_text, prefix_chain
from .scheduler import (
    ClassQueues,
    DEFAULT_CLASS,
    HotPrefixTracker,
    SLO_CLASSES,
    SLO_CLASS_HEADER,
    SloScheduler,
    resolve_slo_class,
)

#: characters per simulated token (matches the router's ~4 chars/token
#: assumption, so chain blocks ≈ 16-token prefix-cache pages)
CHARS_PER_TOKEN = 4


@dataclass
class StubReplicaConfig:
    """One stub replica's capacity/speed model. The defaults make a
    request cost a few ms — fleet-scale traces stay CI-cheap."""

    batch_slots: int = 4          # concurrent decode slots (the Batcher twin)
    max_backlog: int = 32         # admission backlog cap (503 past it)
    token_ms: float = 2.0         # decode wall per generated token
    prefill_ms_per_token: float = 0.05  # prefill wall per COLD prompt token
    slo_ttft_ms: float = 1000.0   # the TTFT target the attainment gauge uses
    admission_timeout_s: float = 30.0   # slot wait before giving up (503)
    # chaos: request fingerprints (server/quarantine.py
    # request_fingerprint over the SAME messages text the gateway hashes)
    # that CRASH this stub — the connection aborts byte-less (the
    # gateway's zero-byte-failure shape) and the replica enters a
    # simulated supervised recovery for `poison_recover_s` (health 503,
    # chat 503) — the engine-wedged failure mode the quarantine exists for
    poison_fps: frozenset = frozenset()
    poison_recover_s: float = 0.3
    # the stub's OWN strike-ledger limit (the real replica builds its
    # ledger from DLT_QUARANTINE_STRIKES; the twin pins it so gateway
    # -restart recovery tests control both tiers): the ledger records
    # poison incidents and serves /debug/quarantine — the gateway's
    # warm-restart recovery source (server/recovery.py)
    quarantine_limit: int = 2
    # tiered-KV twin (runtime/kv_tiering.py): 0 = unbounded warm set
    # (tiering N/A — the pre-tier stub behavior, and the default). With
    # a budget, publishing past it LRU-demotes chain blocks to a
    # host-tier set; a later hit on a demoted block still skips its
    # prefill wall but pays promote_ms_per_token — the cheap host->HBM
    # insert, vs host_chain_budget=0 where eviction deletes and the
    # block re-prefills cold
    hbm_chain_budget: int = 0       # warm chain blocks HBM holds (0 = all)
    host_chain_budget: int = 4096   # demoted blocks the host tier holds
    promote_ms_per_token: float = 0.005  # promotion wall per promoted token


class _Ticket:
    __slots__ = ("klass", "event", "preempt", "progress")

    def __init__(self, klass: str):
        self.klass = klass
        self.event = threading.Event()   # set when a slot is assigned
        self.preempt = threading.Event()  # set when the scheduler evicts us
        self.progress = 0                # tokens generated so far


class _SlotGate:
    """The stub's Batcher twin: ``batch_slots`` concurrent requests,
    waiting tickets drained in SLO-class priority order (interactive
    before standard before batch; FIFO within a class), and preemption —
    a waiting higher-class ticket evicts the lowest-class least-progress
    ACTIVE request (strictly below its class), exactly the live Batcher's
    policy because it IS the live policy object deciding."""

    def __init__(self, cfg: StubReplicaConfig, scheduler: SloScheduler):
        self.cfg = cfg
        self.scheduler = scheduler
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.free = cfg.batch_slots
        # the waiting line IS a ClassQueues — the same structure the live
        # Batcher's backlog uses, so admission_allowed reads it directly
        self.waiting = ClassQueues()
        self.active: set = set()

    def _assign_locked(self):
        while self.free > 0 and len(self.waiting):
            t = self.waiting.popleft()
            self.free -= 1
            self.active.add(t)
            self.scheduler.record(t.klass, "admit")
            t.event.set()

    def depth(self) -> int:
        with self.lock:
            return len(self.waiting)

    def depths(self) -> dict:
        with self.lock:
            return self.waiting.depths()

    def active_count(self) -> int:
        with self.lock:
            return len(self.active)

    def admission_blocked(self, klass: str) -> bool:
        """The REAL policy object's quota/backlog decision over the real
        waiting queues — the twin must never fork the admission math."""
        with self.lock:
            return not self.scheduler.admission_allowed(
                klass, self.waiting, self.cfg.max_backlog
            )

    def acquire(self, klass: str) -> _Ticket | None:
        """Queue for a slot; None = gave up (treated as an overload shed).
        May preempt a strictly-lower-class active request to make room."""
        t = _Ticket(klass)
        with self.lock:
            self.waiting.append(t, klass)
            self._assign_locked()
            if not t.event.is_set() and self.free == 0:
                # at most ONE outstanding preemption per gate: a whole
                # burst of waiters must not massacre every batch row at
                # once — the victim's slot frees within a token wall, and
                # the next waiter re-evaluates then (bounded thrash, the
                # same one-preemption-per-chunk-boundary rule as the live
                # Batcher loop)
                pending = any(a.preempt.is_set() for a in self.active)
                victim = None if pending else self.scheduler.preempt_victim(
                    klass,
                    [(id(a), a.klass, a.progress) for a in self.active],
                )
                if victim is not None:
                    for a in self.active:
                        if id(a) == victim:
                            self.scheduler.record(a.klass, "preempt")
                            a.preempt.set()
                            break
        if not t.event.wait(self.cfg.admission_timeout_s):
            with self.lock:
                try:
                    self.waiting.remove(t, klass)
                    return None
                except ValueError:
                    pass  # assigned between the timeout and the lock:
                    # keep the slot
        return t

    def release(self, t: _Ticket):
        with self.lock:
            self.active.discard(t)
            self.free += 1
            self._assign_locked()


class _StubState:
    """One replica's observable state: counters, warm prefix chains, the
    scheduling policy objects, and per-class goodput/TTFT windows."""

    def __init__(self, cfg: StubReplicaConfig, name: str):
        self.cfg = cfg
        self.name = name
        self.lock = threading.Lock()
        self.counters = {
            "requests_completed": 0, "prefix_hit_tokens": 0,
            "prefix_hits": 0, "shed_503": 0, "client_gone": 0,
            "poison_hits": 0, "supervisor_rebuilds": 0,
        }
        self.recovering_until = 0.0  # monotonic; > now = twin-recovering
        # hard-kill flag (StubEngineReplica.stop): active streams abort
        # their connection at the next token boundary — the wire shape of
        # a replica dying with requests in flight (midstream EOF at the
        # gateway), which shutdown() alone does not produce (handler
        # threads outlive the listening socket)
        self.dying = False
        self.scheduler = SloScheduler()
        self.gate = _SlotGate(cfg, self.scheduler)
        self.hot_prefixes = HotPrefixTracker()
        # the radix cache twin (LRU when cfg.hbm_chain_budget bounds it)
        self.warm_chains: OrderedDict = OrderedDict()
        # the host-tier twin: blocks demoted out of the HBM set. Survives
        # a simulated supervisor rebuild on purpose — host RAM does.
        self.host_chains: OrderedDict = OrderedDict()
        self.wasted: dict = {}             # (reason, class) -> tokens
        self.delivered: dict = {c: 0 for c in SLO_CLASSES}
        self._window: deque = deque()      # (t, n, class), 60 s trim
        self.ttft_ms: dict = {c: deque(maxlen=256) for c in SLO_CLASSES}
        # crash-safe drain hint (POST /admin/drain_hint, the real
        # replica's contract): the draining gateway parks its drain state
        # here; /health carries it back and a warm-restarting gateway
        # restores draining flags + autoscaler ownership from it
        self.draining_hint: dict | None = None
        # the stub's own strike ledger — /debug/quarantine is the
        # gateway's warm-restart recovery source (server/recovery.py)
        self.quarantine = QuarantineLedger(limit=cfg.quarantine_limit)

    def incr(self, name: str, n: int = 1):
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def warm_hit(self, chain) -> tuple:
        """``(hbm_blocks, promoted_blocks)`` — the leading chain blocks
        found warm, walked in order: HBM blocks splice for free,
        host-tier blocks count as hits but charge the promotion wall.
        The walk stops at the first block in neither tier (the radix
        semantics: coverage is a contiguous prefix)."""
        warm = promoted = 0
        with self.lock:
            for ck in chain:
                if ck in self.warm_chains:
                    self.warm_chains.move_to_end(ck)
                    warm += 1
                elif ck in self.host_chains:
                    promoted += 1
                else:
                    break
            if promoted:
                self.counters["kv_tier_hits_host"] = (
                    self.counters.get("kv_tier_hits_host", 0) + 1
                )
        return warm, promoted

    def warm_publish(self, chain):
        """Publish the whole chain into the HBM twin; past
        ``cfg.hbm_chain_budget`` the LRU blocks DEMOTE to the host-tier
        twin (or vanish when ``host_chain_budget`` is 0 — the pre-tier
        delete-on-evict fallback the bench arms compare against)."""
        cfg = self.cfg
        with self.lock:
            for ck in chain:
                self.host_chains.pop(ck, None)  # promoted back up
                self.warm_chains[ck] = True
                self.warm_chains.move_to_end(ck)
            if cfg.hbm_chain_budget <= 0:
                return
            while len(self.warm_chains) > cfg.hbm_chain_budget:
                ck, _ = self.warm_chains.popitem(last=False)
                if cfg.host_chain_budget > 0:
                    self.host_chains[ck] = True
                    self.host_chains.move_to_end(ck)
                    self.counters["kv_tier_demotions"] = (
                        self.counters.get("kv_tier_demotions", 0) + 1
                    )
                    while len(self.host_chains) > cfg.host_chain_budget:
                        self.host_chains.popitem(last=False)

    def add_waste(self, reason: str, klass: str, tokens: int):
        if tokens <= 0:
            return
        with self.lock:
            self.wasted[(reason, klass)] = (
                self.wasted.get((reason, klass), 0) + tokens
            )

    def deliver(self, klass: str, tokens: int):
        now = time.monotonic()
        with self.lock:
            self.delivered[klass] = self.delivered.get(klass, 0) + tokens
            self._window.append((now, tokens, klass))
            while self._window and self._window[0][0] < now - 60.0:
                self._window.popleft()

    def goodput_rows(self) -> list:
        now = time.monotonic()
        with self.lock:
            window = list(self._window)
        if not window:
            return [({}, 0.0)] + [({"slo_class": c}, 0.0) for c in SLO_CLASSES]
        span = max(now - window[0][0], 1.0)
        per = {c: 0 for c in SLO_CLASSES}
        total = 0
        for _, n, c in window:
            total += n
            per[c] = per.get(c, 0) + n
        return [({}, round(total / span, 3))] + [
            ({"slo_class": c}, round(per[c] / span, 3)) for c in SLO_CLASSES
        ]

    def attainment(self, klass: str | None = None) -> float:
        with self.lock:
            if klass is None:
                obs = [v for q in self.ttft_ms.values() for v in q]
            else:
                obs = list(self.ttft_ms[klass])
        if not obs:
            return 1.0
        ok = sum(1 for v in obs if v <= self.cfg.slo_ttft_ms)
        return round(ok / len(obs), 4)


def _render_stub_metrics(st: _StubState) -> str:
    """The stub's ``/metrics`` body — exactly the families the
    FleetScraper lifts (server/fleet.py _GAUGE_SIGNALS/_RATE_SIGNALS) plus
    the scheduler/goodput label families the control plane reads."""
    with st.lock:
        counters = dict(st.counters)
        wasted = dict(st.wasted)
        host_entries = len(st.host_chains)
    gate = st.gate
    lines = []
    for k in ("requests_completed", "prefix_hit_tokens", "shed_503"):
        m = f"dlt_{k}_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(_prom(m, None, counters.get(k, 0)))
    gauges = {
        "dlt_batcher_batch_slots": st.cfg.batch_slots,
        "dlt_batcher_slots_active": gate.active_count(),
        "dlt_batcher_slots_prefilling": 0,
        "dlt_batcher_queue_depth": gate.depth(),
        "dlt_batcher_max_backlog": st.cfg.max_backlog,
        "dlt_slo_tpot_attainment": 1.0,
    }
    for m, v in gauges.items():
        lines.append(f"# TYPE {m} gauge")
        lines.append(_prom(m, None, v))
    lines.append("# TYPE dlt_slo_ttft_attainment gauge")
    lines.append(_prom("dlt_slo_ttft_attainment", None, st.attainment()))
    for c in SLO_CLASSES:
        lines.append(
            _prom("dlt_slo_ttft_attainment", {"slo_class": c}, st.attainment(c))
        )
    lines.append("# TYPE dlt_goodput_tokens_per_s gauge")
    for lab, v in st.goodput_rows():
        lines.append(_prom("dlt_goodput_tokens_per_s", lab or None, v))
    lines.append("# TYPE dlt_wasted_tokens_total counter")
    for (reason, klass), v in sorted(wasted.items()):
        lines.append(
            _prom("dlt_wasted_tokens_total",
                  {"reason": reason, "slo_class": klass}, v)
        )
    lines.append("# TYPE dlt_scheduler_decisions_total counter")
    for lab, v in st.scheduler.decisions_series():
        lines.append(_prom("dlt_scheduler_decisions_total", lab, v))
    if st.cfg.hbm_chain_budget > 0:
        # tiered-KV twin families: the same names the real server emits
        # from TieredKvStore.memory_snapshot(), so the FleetScraper lift
        # and the router's w_tier host-fill term exercise end-to-end
        # against the stub (16 KiB nominal bytes per 16-token block)
        block_b = 16 * 1024
        lines.append("# TYPE dlt_kv_tier_hits_total counter")
        lines.append(_prom("dlt_kv_tier_hits_total", {"tier": "host"},
                           counters.get("kv_tier_hits_host", 0)))
        lines.append("# TYPE dlt_kv_tier_demotions_total counter")
        lines.append(_prom("dlt_kv_tier_demotions_total", {"tier": "host"},
                           counters.get("kv_tier_demotions", 0)))
        tier_gauges = {
            "dlt_kv_tier_host_bytes": host_entries * block_b,
            "dlt_kv_tier_host_budget_bytes":
                max(st.cfg.host_chain_budget, 0) * block_b,
            "dlt_kv_tier_host_entries": host_entries,
        }
        for m, v in tier_gauges.items():
            lines.append(f"# TYPE {m} gauge")
            lines.append(_prom(m, None, v))
    return "\n".join(lines) + "\n"


def parse_qs_n(path: str, default: int = 64) -> int:
    """``?n=`` of a request path (ValueError on garbage, like int())."""
    for part in path.partition("?")[2].split("&"):
        if part.startswith("n="):
            return int(part[2:])
    return default


class StubEngineReplica:
    """One stub replica: start() binds an ephemeral port; the server runs
    a daemon thread per connection (ThreadingHTTPServer) like the real
    batched api server."""

    def __init__(self, cfg: StubReplicaConfig | None = None, name: str = "stub"):
        self.cfg = cfg or StubReplicaConfig()
        self.state = _StubState(self.cfg, name)
        self._httpd: ThreadingHTTPServer | None = None
        self.port = 0

    def start(self) -> "StubEngineReplica":
        st = self.state
        cfg = self.cfg

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _send(self, code: int, body: bytes,
                      ctype="application/json", headers=None):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)
                self.close_connection = True

            def do_GET(self):
                route = self.path.partition("?")[0]
                if route not in ("/metrics",) and time.monotonic() < st.recovering_until:
                    # the supervised-recovery twin: while "rebuilding" the
                    # replica answers 503 with its state (the real
                    # /health contract) — the gateway's breaker and the
                    # fleet table route away; /metrics keeps answering
                    # (the real replica's metrics endpoint does too)
                    self._send(503, json.dumps({
                        "status": "recovering",
                        "counters": dict(st.counters),
                    }).encode())
                    return
                if route == "/metrics":
                    self._send(
                        200, _render_stub_metrics(st).encode(),
                        ctype="text/plain; version=0.0.4",
                    )
                elif route == "/stats":
                    payload = {
                        "batcher": {
                            "batch_slots": cfg.batch_slots,
                            "slots_active": st.gate.active_count(),
                            "queue_depth": st.gate.depth(),
                            "queue_depths": st.gate.depths(),
                            "max_backlog": cfg.max_backlog,
                        },
                        "scheduler": st.scheduler.snapshot(),
                        "batch": cfg.batch_slots,
                        "seq_len": 4096,
                    }
                    self._send(200, json.dumps(payload).encode())
                elif route == "/debug/hot_prefixes":
                    # recovery asks for more than the handoff default —
                    # honor ?n= like the real replica does
                    try:
                        n = int(parse_qs_n(self.path))
                    except ValueError:
                        n = 64
                    snap = st.hot_prefixes.snapshot(top_n=max(1, n))
                    snap["block_chars"] = PAGE_CHARS
                    self._send(200, json.dumps(snap).encode())
                elif route == "/debug/quarantine":
                    # the gateway's warm-restart recovery source: the
                    # full fresh ledger with ages (server/recovery.py)
                    self._send(200, json.dumps(st.quarantine.dump()).encode())
                elif route == "/debug/config":
                    self._send(200, json.dumps({
                        "model": f"stub-{st.name}",
                        "engine": {"batch": cfg.batch_slots},
                    }).encode())
                else:  # /health and anything else health-shaped
                    with st.lock:
                        counters = dict(st.counters)
                        hint = st.draining_hint
                    self._send(200, json.dumps({
                        "status": "ok", "counters": counters,
                        "queue_depth": st.gate.depth(),
                        "draining": hint,
                    }).encode())

            def do_POST(self):
                if self.path.partition("?")[0] == "/admin/drain_hint":
                    # the real replica's crash-safety contract: remember
                    # the drain (and its actuator) for /health readback
                    length = int(self.headers.get("Content-Length", 0))
                    try:
                        hint = json.loads(self.rfile.read(length) or b"{}")
                        draining = bool(hint.get("draining"))
                        by = str(hint.get("by", "operator"))
                    except ValueError:
                        self._send(400, b'{"error":"bad json"}')
                        return
                    with st.lock:
                        st.draining_hint = (
                            {"draining": True, "by": by} if draining else None
                        )
                    self._send(200, b'{"ok": true}')
                    return
                if self.path.partition("?")[0] != "/v1/chat/completions":
                    self._send(404, b'{"error":"not found"}')
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    params = json.loads(self.rfile.read(length) or b"{}")
                    messages = params["messages"]
                except (ValueError, KeyError):
                    self._send(400, b'{"error":"bad request"}')
                    return
                klass = resolve_slo_class(
                    self.headers.get(SLO_CLASS_HEADER)
                    or params.get("slo_class")
                )
                # the ONE hash-text builder (server/router.py) — the twin
                # must never fork the must-hash-identical-text invariant
                text = messages_prefix_text(messages) or ""
                chain = prefix_chain(text)
                st.hot_prefixes.record(chain)
                # chaos: poison requests CRASH the stub (the wedged-engine
                # failure mode) — the connection aborts byte-less, so the
                # gateway sees exactly the zero-byte failure a crashed
                # replica produces, strikes the fingerprint, and retries
                # elsewhere; this replica "rebuilds" for poison_recover_s
                fp = request_fingerprint(text)
                if fp in st.cfg.poison_fps:
                    prompt_tokens = max(len(text) // CHARS_PER_TOKEN, 1)
                    st.incr("poison_hits")
                    st.incr("supervisor_rebuilds")
                    st.add_waste("quarantined", klass, prompt_tokens)
                    # the replica-side strike ledger survives the
                    # simulated rebuild (the real supervisor carries it
                    # over) — /debug/quarantine serves it to recovering
                    # gateways
                    st.quarantine.strike(fp)
                    with st.lock:
                        st.recovering_until = (
                            time.monotonic() + st.cfg.poison_recover_s
                        )
                        st.warm_chains.clear()  # the rebuild's cold cache
                    import socket as _socket

                    try:
                        self.connection.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.close_connection = True
                    return
                if time.monotonic() < st.recovering_until:
                    # mid-"rebuild": innocent arrivals shed cleanly (503 is
                    # never strike evidence — the gateway must not
                    # quarantine a request for landing on a down replica)
                    st.incr("shed_503")
                    self._send(
                        503, b'{"error":"recovering"}',
                        headers={"Retry-After": "1"},
                    )
                    return
                # class-aware admission: the REAL policy object's
                # quota/backlog decision over the gate's real queues —
                # never a forked copy of the math
                if st.gate.admission_blocked(klass):
                    st.incr("shed_503")
                    st.scheduler.record(klass, "shed_backlog")
                    self._send(
                        503, b'{"error":"overloaded"}',
                        headers={"Retry-After": "1"},
                    )
                    return
                t0 = time.perf_counter()
                ticket = st.gate.acquire(klass)
                if ticket is None:
                    st.incr("shed_503")
                    st.scheduler.record(klass, "shed_backlog")
                    self._send(
                        503, b'{"error":"overloaded"}',
                        headers={"Retry-After": "1"},
                    )
                    return
                try:
                    self._serve_generation(params, klass, text, chain,
                                           ticket, t0)
                finally:
                    st.gate.release(ticket)

            def _serve_generation(self, params, klass, text, chain,
                                  ticket, t0):
                prompt_tokens = max(len(text) // CHARS_PER_TOKEN, 1)
                max_tokens = int(params.get("max_tokens") or 16)
                # prefix-cache twin: leading chain blocks already warm on
                # THIS replica skip their prefill wall (16 tokens/block,
                # the page-size equivalence the router is built around);
                # host-tier blocks (runtime/kv_tiering.py twin) also skip
                # it but pay the cheaper promotion wall instead
                warm, promoted = st.warm_hit(chain)
                hit_tokens = min((warm + promoted) * 16, prompt_tokens)
                if hit_tokens:
                    st.incr("prefix_hits")
                    st.incr("prefix_hit_tokens", hit_tokens)
                cold = prompt_tokens - hit_tokens
                time.sleep(
                    (
                        cold * st.cfg.prefill_ms_per_token
                        + promoted * 16 * st.cfg.promote_ms_per_token
                    ) / 1000.0
                )
                if st.dying:
                    # hard-killed DURING prefill: die byte-less — the
                    # zero-byte failure shape the gateway's strike
                    # heuristic sees when a replica crashes holding a
                    # request (the correlated-death false-positive class
                    # the strike discount exists for)
                    import socket as _socket

                    st.add_waste("killed", klass, max(cold, 1))
                    try:
                        self.connection.shutdown(_socket.SHUT_RDWR)
                    except OSError:
                        pass
                    self.close_connection = True
                    return
                st.warm_publish(chain)  # whole chain warm; over-budget LRU demotes
                # SSE decode: one chunk per simulated token
                self.send_response(200)
                self.send_header("Content-Type", "text/event-stream")
                self.send_header("Connection", "close")
                self.end_headers()
                delivered = 0
                outcome = "ok"
                try:
                    for i in range(max_tokens):
                        time.sleep(st.cfg.token_ms / 1000.0)
                        if i == 0:
                            st.ttft_ms[klass].append(
                                (time.perf_counter() - t0) * 1e3
                            )
                        if st.dying:
                            # the replica was hard-killed mid-stream:
                            # abort the connection (the gateway sees a
                            # midstream EOF; the client a truncated
                            # stream it retries elsewhere)
                            import socket as _socket

                            try:
                                self.connection.shutdown(_socket.SHUT_RDWR)
                            except OSError:
                                pass
                            outcome = "killed"
                            break
                        if ticket.preempt.is_set():
                            # preemption mid-stream: the only honest wire
                            # signal is a truncated stream (no [DONE]) —
                            # the same EOF semantics the real gateway has
                            # for mid-stream failures; twin clients detect
                            # it and retry like real clients do
                            outcome = "preempt"
                            break
                        payload = json.dumps({"choices": [{
                            "index": 0,
                            "delta": {"role": "assistant", "content": "t "},
                            "finish_reason": "",
                        }]})
                        self.wfile.write(f"data: {payload}\r\n\r\n".encode())
                        self.wfile.flush()
                        delivered += 1
                        ticket.progress = delivered
                    if outcome == "ok":
                        self.wfile.write(b"data: [DONE]")
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    outcome = "client_gone"
                self.close_connection = True
                if outcome == "ok":
                    st.incr("requests_completed")
                    st.deliver(klass, delivered)
                else:
                    # a preempted or abandoned request's streamed tokens
                    # are waste: part of an answer nobody finished reading
                    if outcome == "client_gone":
                        st.incr("client_gone")
                    st.add_waste(outcome, klass, max(delivered, 1))

        self._handler_cls = Handler
        self._httpd = ThreadingHTTPServer(("127.0.0.1", self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self

    def stop(self):
        self.state.dying = True  # active streams abort at the next token
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    def restart(self) -> "StubEngineReplica":
        """Revive on the SAME port after a kill — the supervised-rejoin
        twin: a fresh server process-equivalent whose prefix cache comes
        back COLD (the real rebuild's fresh radix cache) while the
        replica's counters continue (the real rebuild carries them over)."""
        st = self.state
        with st.lock:
            st.warm_chains.clear()
        st.dying = False
        st.incr("supervisor_rebuilds")
        self._httpd = ThreadingHTTPServer(
            ("127.0.0.1", self.port), self._handler_cls
        )
        threading.Thread(target=self._httpd.serve_forever, daemon=True).start()
        return self


# -- scenario traces ----------------------------------------------------------


@dataclass
class TwinRequest:
    """One scheduled request of a twin trace."""

    at_s: float                 # offset from trace start
    slo_class: str = DEFAULT_CLASS
    system: str = ""            # shared prefix text (system prompt)
    user: str = ""              # unique tail
    max_tokens: int = 16
    abandon_after: int | None = None  # client closes after N tokens
    scenario: str = "chat"


def _words(rng: random.Random, n_chars: int, tag: str) -> str:
    """Deterministic filler text of ~n_chars (seeded, so chain hashes are
    stable per seed)."""
    out = []
    total = 0
    i = 0
    while total < n_chars:
        w = f"{tag}{rng.randrange(1000):03d}"
        out.append(w)
        total += len(w) + 1
        i += 1
    return " ".join(out)


def make_mixed_trace(
    seed: int = 0,
    scale: float = 1.0,
    abandon_p: float = 0.08,
    duration_s: float = 2.0,
) -> list:
    """The standard mixed-scenario trace: chat bursts (interactive),
    shared-prefix RAG fan-out (standard), agentic tool loops with long
    pauses (interactive, growing conversation prefix), and long batch jobs
    — with seeded client abandonment sprinkled across all of it. One
    ``random.Random(seed)`` stream in a fixed draw order (the FaultPlan
    discipline), so a fixed seed replays the identical trace."""
    rng = random.Random(seed)
    reqs: list = []

    def maybe_abandon(max_tokens: int) -> int | None:
        if rng.random() < abandon_p and max_tokens >= 4:
            return rng.randrange(1, max(2, max_tokens // 2))
        return None

    # batch jobs first: long decodes that occupy slots while latency
    # traffic arrives (the contention the scheduler exists to resolve)
    for j in range(max(2, int(4 * scale))):
        sys_txt = _words(rng, 320, f"batchcorpus{j}")
        reqs.append(TwinRequest(
            at_s=rng.uniform(0.0, duration_s * 0.3),
            slo_class="batch", system=sys_txt,
            user=f"summarize shard {j}",
            max_tokens=rng.randrange(120, 200),
            abandon_after=maybe_abandon(160),
            scenario="batch_job",
        ))
    # chat bursts: clumps of interactive turns sharing one app's system
    # prompt, arriving within a ~50 ms window
    for b in range(max(2, int(3 * scale))):
        t0 = rng.uniform(duration_s * 0.2, duration_s * 0.8)
        sys_txt = _words(rng, 260, f"chatapp{b}")
        for i in range(max(3, int(4 * scale))):
            mt = rng.randrange(8, 20)
            reqs.append(TwinRequest(
                at_s=t0 + rng.uniform(0.0, 0.05),
                slo_class="interactive", system=sys_txt,
                user=f"burst {b} turn {i}",
                max_tokens=mt,
                abandon_after=maybe_abandon(mt),
                scenario="chat_burst",
            ))
    # RAG fan-out: many standard requests over ONE long shared corpus
    # prefix (the router-concentration workload)
    rag_sys = _words(rng, 640, "ragcorpus")
    for i in range(max(4, int(6 * scale))):
        mt = rng.randrange(12, 28)
        reqs.append(TwinRequest(
            at_s=rng.uniform(duration_s * 0.1, duration_s * 0.9),
            slo_class="standard", system=rag_sys,
            user=f"rag question {i}",
            max_tokens=mt,
            abandon_after=maybe_abandon(mt),
            scenario="rag_fanout",
        ))
    # agentic tool loops: one conversation, several turns with LONG pauses
    # between them (tool executions), prefix growing each turn
    for a in range(max(1, int(2 * scale))):
        t = rng.uniform(0.0, duration_s * 0.3)
        convo = _words(rng, 200, f"agent{a}")
        for turn in range(3):
            mt = rng.randrange(6, 14)
            reqs.append(TwinRequest(
                at_s=t, slo_class="interactive", system=convo,
                user=f"tool step {turn}",
                max_tokens=mt,
                abandon_after=maybe_abandon(mt),
                scenario="agent_loop",
            ))
            pause = rng.uniform(0.15, 0.4)  # the "tool runs" pause
            t += pause
            convo = convo + " " + _words(rng, 140, f"agent{a}tool{turn}")
    reqs.sort(key=lambda r: r.at_s)
    return reqs


# -- the twin harness ---------------------------------------------------------


@dataclass
class TwinResult:
    """One replayed request's client-side observation."""

    slo_class: str
    scenario: str
    status: int = 0
    ttft_ms: float | None = None
    tokens: int = 0
    outcome: str = "error"  # ok | shed | abandoned | preempted | error
    retries: int = 0
    error: str = ""
    gateway_failovers: int = 0  # addresses skipped before one answered


class TwinGateway:
    """One REAL gateway stack (Balancer + router + fleet scraper +
    optional autoscaler + optional peering) over the twin's stub fleet —
    built through :class:`~.gateway.GatewayServer`, so the twin's gateway
    lifecycle IS the production lifecycle (restart = new instance,
    teardown stops every gateway-owned thread)."""

    def __init__(self, twin: "LoadTwin", index: int, port: int,
                 recover: bool = False):
        from .fleet import FleetScraper
        from .gateway import Backend, Balancer, GatewayConfig, GatewayServer

        self.index = index
        self.port = port
        peers = [
            f"127.0.0.1:{p}" for j, p in enumerate(twin.gateway_ports)
            if j != index
        ]
        self.cfg = GatewayConfig(
            backends=[Backend("127.0.0.1", r.port) for r in twin.replicas],
            # capacity lives in the replicas' slot gates: the gateway's
            # per-backend inflight cap must not serialize the twin ahead
            # of the scheduler under test
            max_inflight_per_backend=twin.max_inflight_per_backend,
            queue_size=256, queue_timeout_s=30.0,
            probe_interval_s=0, fleet_scrape_s=0,  # scraper attached below
            router_policy=twin.router_policy,
            autoscale_s=0,  # autoscaler built (and ticked) explicitly
            quarantine_strikes=twin.quarantine_strikes,
            retry_attempts=twin.retry_attempts,
            breaker_failure_threshold=twin.breaker_failure_threshold,
            peer_gateways=peers or None,
            peer_sync_s=twin.peer_sync_s,
            # deterministic election: gw00 < gw01 < ... — the twin's
            # leader is always the lowest-index LIVE gateway
            gateway_id=f"gw{index:02d}",
            recover_on_start=recover,
        )
        self.balancer = Balancer(self.cfg)
        self.scraper = FleetScraper(
            self.balancer, interval_s=max(twin.fleet_scrape_s, 0.05),
            timeout_s=1.0,
        )
        self.balancer.fleet = self.scraper
        # autoscaler semantics mirror the real gateway: None = absent,
        # 0 = built and attached but manually driven (tick()/drain() —
        # the chaos tests' mode), > 0 = background loop
        self.autoscaler = None
        if twin.autoscale_s is not None:
            from .autoscaler import Autoscaler, AutoscalerConfig

            self.autoscaler = Autoscaler(
                self.balancer,
                config=AutoscalerConfig(
                    interval_s=twin.autoscale_s, cooldown_s=0.0, down_after=2,
                ),
            )
            self.balancer.autoscaler = self.autoscaler
        self.server = GatewayServer(port, self.balancer).start()
        if twin.fleet_scrape_s > 0:
            self.scraper.start()
        if twin.autoscale_s is not None and twin.autoscale_s > 0:
            self.autoscaler.start()
        _wait_listening(port)

    def close(self):
        # GatewayServer stops the threads IT started; the twin attaches
        # its own scraper/autoscaler, so it stops them too
        self.server.server_close()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.scraper.stop()

    def kill(self):
        """Crash-shaped close: also severs every in-flight proxied
        stream (GatewayServer.kill), the wire shape of a real gateway
        process death."""
        self.server.kill()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        self.scraper.stop()


class LoadTwin:
    """N stub replicas behind one or more REAL gateway stacks.
    ``classes_enabled=False`` strips every request to `standard` — the
    no-class baseline arm the bench leg compares against.
    ``n_gateways>1`` builds an ACTIVE-ACTIVE pair/mesh (peered via
    server/peering.py); twin clients spread across the gateways and fail
    over between addresses like production clients."""

    def __init__(
        self,
        n_replicas: int = 10,
        replica_cfg: StubReplicaConfig | None = None,
        router_policy: str = "cache_aware",
        fleet_scrape_s: float = 0.0,
        autoscale_s: float | None = None,
        classes_enabled: bool = True,
        max_inflight_per_backend: int = 64,
        quarantine_strikes: int | None = None,
        retry_attempts: int = 2,
        n_gateways: int = 1,
        peer_sync_s: float | None = None,
        breaker_failure_threshold: int = 3,
    ):
        self.classes_enabled = classes_enabled
        self.router_policy = router_policy
        self.fleet_scrape_s = fleet_scrape_s
        self.autoscale_s = autoscale_s
        self.max_inflight_per_backend = max_inflight_per_backend
        self.quarantine_strikes = quarantine_strikes
        self.retry_attempts = retry_attempts
        self.breaker_failure_threshold = breaker_failure_threshold
        # peer gossip cadence for multi-gateway twins: default one tenth
        # of a second (CI-cheap); pass 0 to attach peering without the
        # push thread (tests drive sync_round() explicitly)
        self.peer_sync_s = (
            peer_sync_s if peer_sync_s is not None
            else (0.1 if n_gateways > 1 else None)
        )
        self.replicas = [
            StubEngineReplica(replica_cfg, name=str(i)).start()
            for i in range(n_replicas)
        ]
        self.gateway_ports = [_free_port() for _ in range(max(n_gateways, 1))]
        self.gateways = [
            TwinGateway(self, i, p)
            for i, p in enumerate(self.gateway_ports)
        ]
        self._rr = 0

    # -- single-gateway compat aliases (gateway 0 is the primary) ------------

    @property
    def port(self) -> int:
        return self.gateway_ports[0]

    @property
    def cfg(self):
        return self.gateways[0].cfg

    @property
    def balancer(self):
        return self.gateways[0].balancer

    @property
    def scraper(self):
        return self.gateways[0].scraper

    @property
    def autoscaler(self):
        return self.gateways[0].autoscaler

    # -- one client -----------------------------------------------------------

    def _client(self, req: TwinRequest, max_attempts: int = 8) -> TwinResult:
        """Real-client semantics: honor 503+Retry-After and retry a
        truncated (preempted) stream, bounded — a preempted batch job's
        work is deferred, not lost, exactly like a production client."""
        res = None
        for attempt in range(max_attempts):
            res = self._attempt(req)
            res.retries = attempt
            if res.outcome == "shed":
                time.sleep(0.05 * (attempt + 1))
                continue
            if res.outcome == "preempted":
                # back off past the burst that evicted us — immediate
                # re-entry would meet the same wave again mid-decode
                time.sleep(0.08 * (attempt + 1))
                continue
            return res
        return res

    def _gateway_order(self) -> list:
        """This attempt's gateway address preference: round-robin over
        the configured addresses (active-active — both gateways serve),
        with the REST of the list as failover targets. Clients know every
        gateway address up front, exactly like a production client behind
        DNS round-robin with client-side failover."""
        ports = list(self.gateway_ports)
        self._rr = (self._rr + 1) % len(ports)
        return ports[self._rr:] + ports[: self._rr]

    def _attempt(self, req: TwinRequest) -> TwinResult:
        res = TwinResult(slo_class=req.slo_class, scenario=req.scenario)
        body = json.dumps({
            "messages": [
                {"role": "system", "content": req.system},
                {"role": "user", "content": req.user},
            ],
            "max_tokens": req.max_tokens,
            "stream": True,
        })
        headers = {"Content-Type": "application/json"}
        if self.classes_enabled:
            headers[SLO_CLASS_HEADER] = req.slo_class
        # client-side gateway failover: an address that cannot even
        # answer the request line (refused mid-restart, reset before the
        # status line) fails over to the next gateway — NOTHING was
        # consumed, so the retry is transparent. Once a status line
        # arrived, in-request failover is over: a mid-stream death is a
        # TRUNCATED stream, re-asked through the ordinary retry loop
        # (which round-robins onto the next address) like a preemption.
        conn = resp = None
        t0 = 0.0
        last_err: OSError | None = None
        for port in self._gateway_order():
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            try:
                t0 = time.perf_counter()
                conn.request("POST", "/v1/chat/completions", body=body,
                             headers=headers)
                resp = conn.getresponse()
                break
            except OSError as e:
                last_err = e
                res.gateway_failovers += 1
                try:
                    conn.close()
                except OSError:
                    pass
                conn = resp = None
        if resp is None:
            res.outcome = "error"
            res.error = repr(last_err)
            return res
        try:
            res.status = resp.status
            if resp.status != 200:
                resp.read()
                if resp.status == 422:
                    # quarantined: TERMINAL by contract — a production
                    # client must not retry a 422 (the request is the
                    # problem), and the twin's retry loop honors that
                    res.outcome = "quarantined"
                elif resp.status == 503:
                    res.outcome = "shed"
                else:
                    res.outcome = "error"
                return res
            first = resp.read(6)  # the leading b"data: " of the first event
            res.ttft_ms = (time.perf_counter() - t0) * 1e3
            buf = b""
            tokens = 0
            while True:
                chunk = resp.read(512)
                if not chunk:
                    break
                buf += chunk
                tokens = buf.count(b"delta")
                if req.abandon_after is not None and tokens >= req.abandon_after:
                    res.outcome = "abandoned"
                    res.tokens = tokens
                    conn.close()  # the client walks away mid-stream
                    return res
            res.tokens = tokens + (1 if first and tokens == 0 else 0)
            # a 200 stream that ended without [DONE] was truncated by a
            # preemption — the caller's retry loop re-queues it
            res.outcome = "ok" if b"[DONE]" in buf else "preempted"
            return res
        except OSError as e:
            # a connection that died AFTER the status line is a truncated
            # stream — the wire shape of a gateway/replica crash mid-body
            # (kill_gateway severs in-flight sockets). Same retry
            # contract as a preemption truncation: the work was cut
            # short, a production SSE client reconnects and re-asks.
            res.outcome = "preempted"
            res.error = repr(e)
            return res
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def run(self, trace) -> list:
        """Replay a trace against the gateway: one client thread per
        request, released at its scheduled offset. Returns TwinResults in
        trace order."""
        results: list = [None] * len(trace)
        t_start = time.perf_counter()
        threads = []

        def one(i, req):
            delay = req.at_s - (time.perf_counter() - t_start)
            if delay > 0:
                time.sleep(delay)
            results[i] = self._client(req)

        for i, req in enumerate(trace):
            th = threading.Thread(target=one, args=(i, req), daemon=True)
            th.start()
            threads.append(th)
        for th in threads:
            th.join(timeout=120)
        self.wall_s = time.perf_counter() - t_start
        return results

    # -- reporting -----------------------------------------------------------

    @staticmethod
    def _pct(vals, p):
        if not vals:
            return None
        vals = sorted(vals)
        return round(vals[min(len(vals) - 1, int(len(vals) * p))], 1)

    def report(self, results, horizon_s: float | None = None) -> dict:
        """Summarize a run. `horizon_s` fixes the goodput denominator to a
        COMMON measurement horizon when comparing two arms: class-aware
        scheduling DEFERS batch work past the trace window (that's the
        point), so rating each arm over its own makespan would read the
        deferred drain as lost goodput — while genuinely lost work still
        shows up as a delivered-token deficit. The raw makespan rides the
        report as `makespan_s` so the deferral itself stays visible."""
        per_class: dict = {}
        failures = 0
        delivered = 0
        for r in results:
            if r is None:
                failures += 1
                continue
            c = per_class.setdefault(r.slo_class, {
                "n": 0, "ok": 0, "shed": 0, "abandoned": 0, "preempted": 0,
                "quarantined": 0, "error": 0, "ttfts": [], "tokens": 0,
                "retries": 0,
            })
            c["n"] += 1
            c[r.outcome if r.outcome in
              ("ok", "shed", "abandoned", "preempted", "quarantined",
               "error")
              else "error"] += 1
            c["retries"] += r.retries
            if r.outcome in ("ok", "abandoned") and r.ttft_ms is not None:
                c["ttfts"].append(r.ttft_ms)
            if r.outcome == "ok":
                c["tokens"] += r.tokens
                delivered += r.tokens
            if r.outcome == "error":
                failures += 1
        out = {"classes": {}, "failures": failures}
        for k, c in per_class.items():
            out["classes"][k] = {
                "n": c["n"], "ok": c["ok"], "shed": c["shed"],
                "abandoned": c["abandoned"], "preempted": c["preempted"],
                "quarantined": c["quarantined"], "error": c["error"],
                "retries": c["retries"],
                "delivered_tokens": c["tokens"],
                "ttft_p50_ms": self._pct(c["ttfts"], 0.50),
                "ttft_p95_ms": self._pct(c["ttfts"], 0.95),
            }
        out["delivered_tokens"] = delivered
        wall = max(getattr(self, "wall_s", 1.0), 1e-6)
        out["makespan_s"] = round(wall, 3)
        out["goodput_tokens_per_s"] = round(
            delivered / max(wall, horizon_s or 0.0), 1
        )
        out["fleet_prefix_hit_tokens"] = self.fleet_prefix_hit_tokens()
        return out

    # -- chaos controls -------------------------------------------------------

    def kill_gateway(self, i: int):
        """Hard-kill one gateway mid-run: its socket closes (new
        connections refuse — twin clients fail over to the next address),
        every gateway-owned thread stops, AND every in-flight proxied
        stream is severed mid-body (a process crash takes the handler
        threads with it) — exactly the crash the warm-restart recovery
        exists for. Clients see the truncation and retry like any other
        truncated stream."""
        self.gateways[i].kill()

    def restart_gateway(self, i: int, recover: bool = True):
        """Bring a killed gateway back on its port as a FRESH instance —
        the crash-only restart: a new Balancer (cold breakers), a new
        router, and (with ``recover=True``, the production default for
        fleet-aware gateways) the server/recovery.py warm-restart sweep
        rebuilding locality/quarantine/drain state from the fleet before
        the first proxied request. ``recover=False`` is the cold-gateway
        baseline arm the acceptance test compares against."""
        self.gateways[i] = TwinGateway(
            self, i, self.gateway_ports[i], recover=recover
        )
        return self.gateways[i]

    def sync_gateways(self):
        """One manual gossip round from every live gateway (tests that
        attach peering without the push thread drive this)."""
        for gw in self.gateways:
            peering = gw.balancer.peering
            if peering is not None:
                peering.sync_round()

    def partition_gateways(self):
        """Split-brain chaos: drop gossip posts between ALL gateways, both
        directions (each side keeps serving and accumulating deltas — the
        at-most-once proof runs across the healed merge)."""
        for gw in self.gateways:
            peering = gw.balancer.peering
            if peering is not None:
                peering.partition()

    def heal_gateways(self):
        """End the split: the next sync round delivers each side's backlog."""
        for gw in self.gateways:
            peering = gw.balancer.peering
            if peering is not None:
                peering.heal()

    def kill_replica(self, i: int):
        """Hard-kill one stub mid-run: in-flight streams truncate (the
        gateway's midstream-failure shape), new connections refuse — the
        replica-crash chaos scenario."""
        self.replicas[i].stop()

    def revive_replica(self, i: int):
        """Bring a killed stub back on its port (supervised rejoin: cold
        prefix cache, continuing counters). The gateway's breaker
        re-admits it through the ordinary half-open trial."""
        self.replicas[i].restart()

    def poisoned_replica_count(self) -> int:
        """How many replicas a poison request EVER took down — the
        quarantine acceptance bound (must stay <= the strike limit)."""
        return sum(
            1 for r in self.replicas
            if r.state.counters.get("poison_hits", 0) > 0
        )

    def quarantined_waste_tokens(self) -> int:
        return sum(
            v
            for r in self.replicas
            for (reason, _), v in r.state.wasted.items()
            if reason == "quarantined"
        )

    def fleet_prefix_hit_tokens(self) -> int:
        return sum(
            r.state.counters.get("prefix_hit_tokens", 0)
            for r in self.replicas
        )

    def replica_keys(self) -> list:
        return [b.key for b in self.cfg.backends]

    def close(self):
        for gw in self.gateways:
            gw.close()
        for r in self.replicas:
            r.stop()


def _free_port() -> int:
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_listening(port: int, timeout: float = 5.0):
    import socket

    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port), timeout=0.2).close()
            return
        except OSError:
            time.sleep(0.02)
    raise RuntimeError(f"gateway on {port} never came up")
