"""Cache-aware routing: prefix-locality placement over the fleet signal plane.

The reference gateway — and this one until now — picks least-inflight
(reference: dllama-gateway.cpp:266-301): it balances *load* but is blind to
*state*. Serving traffic is dominated by shared prefixes (system prompts,
few-shot templates, multi-turn histories), and every replica keeps a radix
prefix cache of published KV (runtime/prefix_cache.py) — so WHERE a request
lands decides whether its prompt re-prefills from token 0 or splices cached
KV. Least-inflight sprays a shared prefix across the fleet and every replica
pays the cold prefill once; cache-aware routing (SGLang's cache-aware policy
over radix caches; DistServe frames the placement half) lands it on the ONE
replica whose cache already holds it.

Mechanics — all host-side, stdlib-only (the gateway imports this and must
run on a box with no jax):

* **prefix hash chain** — the leading text of the request's chat messages is
  hashed in fixed-size blocks (:data:`PAGE_CHARS` characters ≈ the prefix
  cache's 16-token pages at ~4 chars/token), each block chained onto the
  previous hash (FNV-1a): ``chain[i]`` names the first ``i+1`` blocks, so
  two requests sharing a prefix share a chain prefix — the same structure
  the radix trie keys on, approximated pre-tokenization;
* **locality map** — a bounded LRU of ``chain key -> backend`` learned from
  this gateway's own routing decisions: the deepest known chain key names
  the replica whose cache most specifically holds the prefix;
* **rendezvous owner** — cold prefixes (no locality entry) fall to
  highest-random-weight hashing over the live backends: deterministic, and
  a replica join/leave remaps only the keys the changed replica owned
  (~1/n), never reshuffles the rest — the affinity-stability property the
  tests pin;
* **fleet-signal scoring** — :func:`score_backend` (a pure function) folds
  the PR 9 signal table into the rank: KV-pool headroom, batcher occupancy,
  TTFT-SLO attainment — *discounted to zero when the replica's signals are
  stale* (the scraper aged out), so a silent replica never wins on
  last-known numbers. Prefix affinity is NOT staleness-discounted: cache
  contents outlive a scrape gap;
* **fallback** — with no parseable prefix, no affinity, and stale signals,
  the router abstains and the balancer's least-inflight selection stands.

Every decision is counted by reason (``dlt_router_decisions_total{reason=
prefix_affinity|headroom|fallback_stale|least_inflight}`` on the gateway's
``/metrics``), traced per request (``gw_route`` with the scored candidates),
and summarized in the ``router`` section of ``GET /gateway/fleet``.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

#: characters per hash block — the prefix cache publishes at 16-token pages
#: and text runs ~4 chars/token, so one block approximates one page
PAGE_CHARS = 64
#: chain depth cap: prefixes deeper than this share their fate with the
#: 32-block (≈2k-char) chain head — long-tail depth adds nothing to routing
MAX_BLOCKS = 32

#: every reason `dlt_router_decisions_total` is labeled with — the zero
#: -valued reasons always render, so dashboards never see a series appear
#: from nowhere mid-incident
REASONS = ("prefix_affinity", "headroom", "fallback_stale", "least_inflight")

_FNV64_OFFSET = 0xCBF29CE484222325
_FNV64_PRIME = 0x100000001B3

#: router prefetch hint (runtime/kv_tiering.py): the gateway forwards the
#: plan's chain keys on every proxied chat request as comma-joined
#: zero-padded hex, so the backend's tiered KV store can lift the matching
#: prefix disk/peer -> host BEFORE (or while) the prompt is tokenized.
#: Purely advisory — stripping the header costs warmth, never correctness.
PREFETCH_CHAIN_HEADER = "X-DLT-Prefetch-Chain"


def chain_header_value(chain) -> str:
    """Wire-encode router chain keys for :data:`PREFETCH_CHAIN_HEADER` —
    the same zero-padded hex ``/debug/hot_prefixes`` speaks."""
    return ",".join(f"{ck:016x}" for ck in chain)


def parse_chain_header(value) -> list:
    """Decode a :data:`PREFETCH_CHAIN_HEADER` value back to chain keys.
    Garbage (missing, empty, non-hex fragments) degrades to ``[]`` — a
    prefetch hint must never be able to fail a request."""
    if not value:
        return []
    try:
        return [int(p, 16) for p in str(value).split(",") if p.strip()]
    except ValueError:
        return []


def fnv1a(data: bytes, h: int = _FNV64_OFFSET) -> int:
    """64-bit FNV-1a over ``data`` seeded with ``h`` — deterministic across
    processes and runs (Python's builtin hash is salted per process, which
    would break cross-gateway agreement on prefix ownership)."""
    for b in data:
        h ^= b
        h = (h * _FNV64_PRIME) & 0xFFFFFFFFFFFFFFFF
    return h


def prefix_chain(text: str, block_chars: int = PAGE_CHARS,
                 max_blocks: int = MAX_BLOCKS) -> list:
    """Chained block hashes of the leading text: ``chain[i]`` covers the
    first ``i+1`` blocks, and each hash seeds the next — so texts sharing a
    leading span share exactly the chain entries that span covers. Only
    COMPLETE blocks hash (a half-filled tail block would make the chain key
    depend on where the request happens to end, splitting identical
    prefixes across keys)."""
    out: list = []
    h = _FNV64_OFFSET
    n_full = min(len(text) // block_chars, max_blocks)
    for i in range(n_full):
        h = fnv1a(
            text[i * block_chars : (i + 1) * block_chars].encode(
                "utf-8", errors="replace"
            ),
            h,
        )
        out.append(h)
    return out


def messages_prefix_text(messages) -> str | None:
    """The routable prefix text of a parsed ``messages`` list: roles +
    contents concatenated in order (the same order the chat template feeds
    the tokenizer, so equal text here means equal leading tokens there).
    Shared by the gateway's router (via :func:`chat_prefix_text`) and the
    replica-side hot-prefix tracker (server/api.py) — BOTH sides must hash
    the identical text or warm-handoff chain keys would never match the
    locality map's. None on garbage shapes (non-list, non-dict entries)."""
    try:
        parts = []
        for m in messages:
            parts.append(str(m.get("role", "")))
            parts.append("\x00")
            parts.append(str(m.get("content", "")))
            parts.append("\x1e")
        return "".join(parts)
    except (TypeError, AttributeError):
        # AttributeError included: a JSON-valid body whose messages entries
        # are not dicts ({"messages": ["hi"]}) must abstain, not crash the
        # gateway's connection thread — the backend owns the 400
        return None


def chat_prefix_text(body: bytes) -> str | None:
    """The routable prefix text of a raw ``/v1/chat/completions`` body.
    None = not a routable chat request (bad JSON, no messages) — the
    caller falls back to least-inflight."""
    try:
        messages = json.loads(body)["messages"]
    except (ValueError, KeyError, TypeError):
        return None
    return messages_prefix_text(messages)


def rendezvous_owner(key: int, backends: list) -> str | None:
    """Highest-random-weight owner of ``key`` among ``backends`` (keys are
    backend ``host:port`` strings). Adding/removing a backend remaps only
    the keys the changed backend wins — every other key's owner is decided
    by a pairwise comparison the change didn't touch."""
    best, best_w = None, -1
    for b in backends:
        w = fnv1a(b.encode(), key)
        if w > best_w:
            best, best_w = b, w
    return best


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class RouterConfig:
    """Routing knobs (``DLT_ROUTER_*`` envs; the gateway's ``--router``
    flag picks the policy). Weights are unitless score points — affinity
    must dominate the sum of the signal terms so a known-warm cache beats
    any amount of idle headroom, while the inflight penalty lets a truly
    swamped affinity replica lose to an idle one."""

    policy: str = "cache_aware"  # cache_aware | least_inflight (= off)
    locality_size: int = 4096    # LRU entries in the chain-key -> backend map
    w_affinity: float = 4.0      # expected-prefix-hit bonus
    w_headroom: float = 1.0      # KV-pool free-page fraction
    w_occupancy: float = 1.0     # 1 - batcher slot occupancy
    w_slo: float = 1.0           # TTFT-SLO attainment
    w_inflight: float = 0.5      # per-inflight-request penalty
    w_tier: float = 0.5          # host-tier occupancy bonus (tiered KV)

    @classmethod
    def resolve(cls, policy: str | None = None) -> "RouterConfig":
        """Env-driven construction: an explicit ``policy`` wins, then
        ``DLT_ROUTER`` (default cache_aware — the serving tier's point)."""
        return cls(
            policy=policy or os.environ.get("DLT_ROUTER", "cache_aware"),
            locality_size=_env_int("DLT_ROUTER_LOCALITY", 4096),
            w_affinity=_env_float("DLT_ROUTER_W_AFFINITY", 4.0),
            w_headroom=_env_float("DLT_ROUTER_W_HEADROOM", 1.0),
            w_occupancy=_env_float("DLT_ROUTER_W_OCCUPANCY", 1.0),
            w_slo=_env_float("DLT_ROUTER_W_SLO", 1.0),
            w_inflight=_env_float("DLT_ROUTER_W_INFLIGHT", 0.5),
            w_tier=_env_float("DLT_ROUTER_W_TIER", 0.5),
        )


def score_backend(
    affinity: bool,
    signals: dict,
    stale: bool,
    inflight: int,
    cfg: RouterConfig,
) -> float:
    """The PURE scoring function every routing decision ranks with.

    * ``affinity`` — this backend is the prefix's locality/rendezvous owner
      (expected prefix hit). NOT staleness-discounted: cached KV outlives a
      scrape gap, and the cost of re-prefilling elsewhere is certain;
    * ``signals``/``stale`` — the fleet table's last-known row and its
      freshness. A stale row contributes ZERO signal score (the stale
      discount): last-known headroom on a silent replica is a guess, and
      guessing high is how a dead replica keeps winning traffic. Fresh rows
      score KV-pool headroom (free-page fraction; contiguous replicas
      without a pool get full credit — they cannot exhaust), batcher
      occupancy (free-slot fraction), TTFT-SLO attainment, and — on
      replicas running the tiered KV store (runtime/kv_tiering.py) —
      host-tier fill (warm-but-demoted prefixes this replica can promote
      without a prefill; replicas without a tier score zero here, so the
      term is a tie-breaker among tiered replicas, never a penalty on
      untiered ones), each capped at its weight so no single signal can
      swamp the others;
    * ``inflight`` — the balancer's live connection count, a penalty in
      both regimes (it is the one signal that is never stale)."""
    s = 0.0
    if affinity:
        s += cfg.w_affinity
    if not stale and signals:
        free = signals.get("kv_pool_pages_free")
        if free is not None:
            total = free + signals.get("kv_pool_pages_used", 0)
            s += cfg.w_headroom * (free / total if total > 0 else 1.0)
        else:
            s += cfg.w_headroom
        slots = signals.get("batcher_batch_slots")
        if slots:
            active = min(signals.get("batcher_slots_active", 0), slots)
            s += cfg.w_occupancy * (1.0 - active / slots)
        else:
            s += cfg.w_occupancy
        slo = signals.get("slo_ttft_attainment")
        s += cfg.w_slo * (slo if slo is not None else 1.0)
        tier_budget = signals.get("kv_tier_host_budget_bytes")
        if tier_budget:
            fill = signals.get("kv_tier_host_bytes", 0) / tier_budget
            s += cfg.w_tier * min(max(fill, 0.0), 1.0)
    s -= cfg.w_inflight * inflight
    return s


@dataclass
class RoutePlan:
    """One request's routing verdict: ``ranked`` backend indexes (best
    first — the balancer tries them in order before falling back to
    least-inflight), the affinity/top-signal keys the reason resolution
    compares the actual choice against, the chain keys to learn from the
    outcome, and the scored candidates for the ``gw_route`` trace event."""

    ranked: list = field(default_factory=list)       # backend indexes
    affinity_key: str | None = None                  # locality/rendezvous owner
    best_signal_key: str | None = None               # top fresh-signal backend
    fresh: bool = False                              # any non-stale signal row
    chain: list = field(default_factory=list)        # this prefix's chain keys
    scored: list = field(default_factory=list)       # (backend_key, score)


#: plan(text=...) sentinel: None is a meaningful value (unparsable body)
_NO_TEXT = object()


class Router:
    """Per-gateway routing state: the locality map, the decision counters,
    and the plan/resolve pair the gateway's request loop calls. Thread-safe
    (one lock around the locality map and counters — both are a dict touch
    per REQUEST, never per token)."""

    def __init__(self, cfg: RouterConfig | None = None):
        self.cfg = cfg or RouterConfig()
        self._lock = threading.Lock()
        self._locality: "OrderedDict[int, str]" = OrderedDict()
        self.decisions = {r: 0 for r in REASONS}
        # drain/handoff bookkeeping (under _lock): how many learned chain
        # keys were re-homed (to a surviving rendezvous owner) or purged
        # (no survivor) when a backend drained, plus the warm-handoff keys
        # the autoscaler re-homed from /debug/hot_prefixes snapshots —
        # dlt_router_handoff_rehomed_keys_total / _locality_purged_keys on
        # the gateway's /metrics
        self.handoff = {
            "rehomed_keys": 0, "purged_keys": 0, "drain_events": 0,
        }

    @classmethod
    def build(cls, policy: str | None = None) -> "Router | None":
        """The gateway's factory: None when routing is OFF (policy
        least_inflight/off) — the request loop then skips planning
        entirely and the legacy selection stands. Unknown policies raise:
        a typo'd DLT_ROUTER silently serving cache_aware (or silently NOT
        serving it) would defeat the operator's intent."""
        cfg = RouterConfig.resolve(policy)
        if cfg.policy in ("least_inflight", "off", ""):
            return None
        if cfg.policy != "cache_aware":
            raise ValueError(
                f"unknown router policy {cfg.policy!r} "
                "(one of: cache_aware, least_inflight, off)"
            )
        return cls(cfg)

    # -- planning ------------------------------------------------------------

    def plan(self, body: bytes | None, balancer,
             text=_NO_TEXT) -> RoutePlan | None:
        """Rank the backends for one request. None = the router abstains
        (non-chat request, unparsable body, or a prompt too short to carry
        a full hash block) and the decision counts as least_inflight.
        ``text`` lets a caller that already parsed the body (the gateway
        parses once per request — fingerprint, slo_class, and this plan
        all come off one json.loads) pass the hash text in; omitted, the
        body is parsed here."""
        if text is _NO_TEXT:
            text = chat_prefix_text(body) if body else None
        if text is None:
            return None
        chain = prefix_chain(text)
        if not chain:
            return None
        backends = list(balancer.config.backends)
        keys = [b.key for b in backends if not b.draining]
        if not keys:
            return None
        # affinity: deepest learned chain key first (most specific), the
        # rendezvous owner of the chain HEAD for cold prefixes — the head
        # block is what unrelated requests sharing a system prompt share,
        # so the cold placement already co-locates them
        affinity_key = None
        with self._lock:
            for ck in reversed(chain):
                owner = self._locality.get(ck)
                if owner is not None and owner in keys:
                    affinity_key = owner
                    self._locality.move_to_end(ck)
                    break
        if affinity_key is None:
            affinity_key = rendezvous_owner(chain[0], keys)
        fleet = getattr(balancer, "fleet", None)
        rows = fleet.router_signals() if fleet is not None else {}
        scored = []
        best_signal_key, best_signal = None, None
        fresh = False
        with balancer.lock:
            inflight = {b.key: b.inflight for b in backends}
        for b in backends:
            if b.draining:
                continue
            row = rows.get(b.key) or {}
            stale = bool(row.get("stale", True))
            signals = row.get("signals") or {}
            if not stale:
                fresh = True
                sig = score_backend(False, signals, False, 0, self.cfg)
                if best_signal is None or sig > best_signal:
                    best_signal, best_signal_key = sig, b.key
            score = score_backend(
                b.key == affinity_key, signals, stale,
                inflight.get(b.key, 0), self.cfg,
            )
            scored.append((b.key, score))
        if not scored:
            return None
        order = sorted(
            range(len(scored)), key=lambda i: scored[i][1], reverse=True
        )
        key_to_idx = {b.key: i for i, b in enumerate(backends)}
        return RoutePlan(
            ranked=[key_to_idx[scored[i][0]] for i in order],
            affinity_key=affinity_key,
            best_signal_key=best_signal_key,
            fresh=fresh,
            chain=chain,
            scored=[(k, round(s, 3)) for k, s in scored],
        )

    # -- outcome -------------------------------------------------------------

    def resolve(self, plan: RoutePlan | None, chosen_key: str) -> str:
        """Attribute a completed selection to its reason and count it. The
        chosen backend can differ from the plan's favorite (saturated,
        breaker open): that is a least_inflight outcome, honestly counted.
        Locality is learned separately (:meth:`learn`, on request SUCCESS)
        — counting a selection must not teach the map a backend that is
        about to fail the request zero-byte."""
        if plan is None:
            reason = "least_inflight"
        elif chosen_key == plan.affinity_key:
            reason = "prefix_affinity"
        elif not plan.fresh:
            reason = "fallback_stale"
        elif chosen_key == plan.best_signal_key:
            reason = "headroom"
        else:
            reason = "least_inflight"
        with self._lock:
            self.decisions[reason] += 1
        return reason

    def learn(self, plan: RoutePlan | None, chosen_key: str) -> None:
        """Record the locality of a SUCCESSFUL request: every chain key now
        names the replica that served it — its radix cache holds the prefix
        once the request publishes. Called by the gateway after the proxied
        attempt succeeds, never for failed attempts (a dead backend must
        not become the prefix's learned home)."""
        if plan is None:
            return
        with self._lock:
            for ck in plan.chain:
                self._locality[ck] = chosen_key
                self._locality.move_to_end(ck)
            while len(self._locality) > self.cfg.locality_size:
                self._locality.popitem(last=False)

    # -- drain hygiene + warm handoff ----------------------------------------

    def forget_backend(self, key: str, remaining=None) -> dict:
        """Locality hygiene on drain/leave (Balancer.set_draining calls
        this): every learned chain key whose home is ``key`` is re-homed to
        its rendezvous owner among ``remaining`` backends — or dropped when
        none survive. Without this, every affinity lookup for those chains
        scores a dead home first: `plan` skips draining backends, so the
        stale entry silently degrades every shared-prefix request to
        rendezvous-of-the-head instead of ONE consistent new home."""
        rehomed = purged = 0
        remaining = [k for k in (remaining or []) if k != key]
        with self._lock:
            for ck, owner in list(self._locality.items()):
                if owner != key:
                    continue
                if remaining:
                    self._locality[ck] = rendezvous_owner(ck, remaining)
                    rehomed += 1
                else:
                    del self._locality[ck]
                    purged += 1
            self.handoff["rehomed_keys"] += rehomed
            self.handoff["purged_keys"] += purged
            self.handoff["drain_events"] += 1
        return {"rehomed": rehomed, "purged": purged}

    def rehome_keys(self, hex_keys, remaining, from_key: str | None = None) -> int:
        """Warm drain handoff (server/autoscaler.py): point each chain key
        from a draining replica's ``/debug/hot_prefixes`` snapshot at its
        rendezvous owner among the surviving backends — BEFORE the drain
        lands — so the fleet's shared-prefix traffic re-concentrates on
        one new home (one cold prefill per chain, then hits again) instead
        of spraying cold across the fleet. A chain whose learned home is a
        SURVIVING backend (other than ``from_key``) is left alone: the
        draining replica may have served it once, but the warm affinity
        elsewhere is still correct and must not be evicted. Returns the
        keys re-homed."""
        remaining = list(remaining)
        if not remaining:
            return 0
        n = 0
        with self._lock:
            for hk in hex_keys:
                try:
                    ck = int(hk, 16)
                except (TypeError, ValueError):
                    continue
                owner = self._locality.get(ck)
                if owner is not None and owner != from_key \
                        and owner in remaining:
                    continue  # a healthy replica's warm home stands
                self._locality[ck] = rendezvous_owner(ck, remaining)
                self._locality.move_to_end(ck)
                n += 1
            while len(self._locality) > self.cfg.locality_size:
                self._locality.popitem(last=False)
            self.handoff["rehomed_keys"] += n
        return n

    def handoff_snapshot(self) -> dict:
        with self._lock:
            return dict(self.handoff)

    # -- crash-only recovery + peering (server/recovery.py, peering.py) ------

    def set_owner(self, key: int, backend: str) -> None:
        """Write one locality entry WITHOUT handoff accounting — the
        peer-sync / warm-restart write path (a re-learned entry is not a
        drain event; counting it would make the handoff counters lie
        about what the autoscaler did)."""
        with self._lock:
            self._locality[key] = backend
            self._locality.move_to_end(key)
            while len(self._locality) > self.cfg.locality_size:
                self._locality.popitem(last=False)

    def prime_locality(self, owners: dict) -> int:
        """Warm-restart repopulation (server/recovery.py): bulk-load
        ``{chain_key_int: backend_key}`` re-learned from the fleet's
        ``/debug/hot_prefixes`` snapshots. Returns the entries written."""
        with self._lock:
            for ck, backend in owners.items():
                self._locality[ck] = backend
                self._locality.move_to_end(ck)
            while len(self._locality) > self.cfg.locality_size:
                self._locality.popitem(last=False)
        return len(owners)

    def owner_of(self, key: int) -> str | None:
        """The learned home of one chain key (None when unknown) — the
        peering LWW apply reads this to report, never to decide (versions
        live in server/peering.py)."""
        with self._lock:
            return self._locality.get(key)

    # -- views ---------------------------------------------------------------

    def decisions_snapshot(self) -> dict:
        with self._lock:
            return dict(self.decisions)

    def snapshot(self) -> dict:
        """The ``router`` section of ``GET /gateway/fleet``."""
        with self._lock:
            return {
                "policy": self.cfg.policy,
                "decisions": dict(self.decisions),
                "locality_entries": len(self._locality),
                "locality_size": self.cfg.locality_size,
                "handoff": dict(self.handoff),
                "weights": {
                    "affinity": self.cfg.w_affinity,
                    "headroom": self.cfg.w_headroom,
                    "occupancy": self.cfg.w_occupancy,
                    "slo": self.cfg.w_slo,
                    "inflight": self.cfg.w_inflight,
                },
                "block_chars": PAGE_CHARS,
            }
