"""Fault-injection harness: a deterministic chaos proxy for serving tests.

The robustness layer (gateway breakers, zero-byte retry, active probes,
engine stall recovery) is only trustworthy if its failure modes are
reproducible ON DEMAND — waiting for a real TPU host to die is not a test
plan. `ChaosProxy` fronts a real backend and injects scripted faults at the
TCP layer, so a test can state "backend 2 resets every stream after 100
bytes from request 3 on" and assert the exact client-visible outcome.

Fault modes (each maps to a distinct real-world failure):

* ``refuse``        — accept and immediately RST (dead service; the OS
                      accept queue makes a true pre-accept refusal
                      unscriptable per-connection, so the reset lands on
                      the client's first read/write). For a true
                      ECONNREFUSED use :meth:`ChaosProxy.down`, which
                      closes the listener entirely (host down);
* ``reset_on_accept`` — read the full request, then RST before any
                      response byte (backend crashed mid-handling);
* ``midstream_reset`` — proxy normally, forward ``after_bytes`` of the
                      response, then RST (backend crashed mid-stream);
* ``stall``         — read the request, then hold the connection silent
                      for ``delay_s`` before RST (slow-loris / wedged
                      runtime; exercises upstream read timeouts);
* ``latency``       — sleep ``delay_s``, then proxy transparently (slow
                      network; request still succeeds);
* ``pass``          — transparent proxy.

Corruption modes (the WRONG-DATA faults — delivered complete, so only a
content check can catch them; the data-plane integrity layer's chaos twin,
runtime/kv_transport.py verify_transfer):

* ``bitflip``       — flip one bit of the response body at offset
                      ``after_bytes`` (0 = the middle) — a bad NIC/DMA;
* ``truncate_body`` — keep ``after_bytes`` of the body (0 = half) and
                      REWRITE Content-Length to match, so the truncation
                      parses as a complete response instead of dying as an
                      IncompleteRead — a buggy sender, not a dead one;
* ``garbage_header`` — overwrite the body's leading bytes (the KV codec's
                      length prefix + JSON header region) with garbage —
                      a stale/foreign payload on a reused port.

Corrupting faults buffer the whole upstream response (they must parse and
rewrite it) instead of streaming it chunk-by-chunk.

Faults are scheduled by a `FaultPlan`: explicit per-connection rules keyed
on the proxy's accept counter, an optional default, and an optional seeded
random mix. Connection indices are assigned in accept order under a single
accept loop, so a fixed plan (and fixed seed) replays the same fault
sequence every run — determinism is the whole point.

Example — "backend dies on request 3, recovers after 2 s"::

    plan = FaultPlan(rules={3: Fault(REFUSE)})
    proxy = ChaosProxy("127.0.0.1", backend_port, plan)
    proxy.start()
    ...
    proxy.down()          # host vanishes: connections now refused
    time.sleep(2.0)
    proxy.up()            # host back; gateway's prober re-admits it
"""

from __future__ import annotations

import random
import socket
import struct
import threading
import time
from dataclasses import dataclass, field

# one HTTP-request framer for the whole serving layer: the harness must
# read requests EXACTLY the way the gateway it exercises does, or the two
# drift apart on framing edge cases
from .gateway import _read_http_request as _read_request

PASS = "pass"
REFUSE = "refuse"
RESET_ON_ACCEPT = "reset_on_accept"
MIDSTREAM_RESET = "midstream_reset"
STALL = "stall"
LATENCY = "latency"
BITFLIP = "bitflip"
TRUNCATE_BODY = "truncate_body"
GARBAGE_HEADER = "garbage_header"

_CORRUPT_KINDS = {BITFLIP, TRUNCATE_BODY, GARBAGE_HEADER}
_KINDS = {
    PASS, REFUSE, RESET_ON_ACCEPT, MIDSTREAM_RESET, STALL, LATENCY,
} | _CORRUPT_KINDS


@dataclass(frozen=True)
class Fault:
    kind: str = PASS
    after_bytes: int = 0  # midstream_reset: response bytes forwarded before
    # RST; bitflip: body offset of the flipped bit (0 = middle);
    # truncate_body: body bytes kept (0 = half)
    delay_s: float = 0.0  # stall: silence duration; latency: added delay

    def __post_init__(self):
        if self.kind not in _KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")


@dataclass
class FaultPlan:
    """Deterministic fault schedule over the proxy's accept counter.

    * ``rules``: explicit per-connection faults — ``{3: Fault(REFUSE)}``
      injects on the 4th accepted connection (0-indexed);
    * ``default``: fault for connections with no rule (``Fault(PASS)``);
    * ``random_mix`` + ``seed``: optional seeded randomness — each unruled
      connection draws from ``random.Random(seed)`` and picks the first
      ``(probability, fault)`` whose cumulative range covers the draw.
      The stream is indexed by accept order, so a fixed seed replays the
      identical fault sequence.
    """

    rules: dict[int, Fault] = field(default_factory=dict)
    default: Fault = field(default_factory=Fault)
    random_mix: list[tuple[float, Fault]] = field(default_factory=list)
    seed: int = 0

    def __post_init__(self):
        self._rng = random.Random(self.seed)

    def fault_for(self, conn_index: int) -> Fault:
        # one draw per connection, rule hit or not: adding a rule to a
        # seeded plan must not SHIFT the random stream under every later
        # connection (the draw happens even when a rule overrides it)
        draw = self._rng.random() if self.random_mix else 0.0
        if conn_index in self.rules:
            return self.rules[conn_index]
        acc = 0.0
        for p, fault in self.random_mix:
            acc += p
            if draw < acc:
                return fault
        return self.default


def _set_content_length(head: bytes, n: int) -> bytes:
    """Rewrite the Content-Length line of a buffered response head — a
    corrupted body must still FRAME as a complete response (the wrong-data
    contract: the transport delivers, only the content check can object)."""
    lines = head.split(b"\r\n")
    for i, ln in enumerate(lines):
        if ln.lower().startswith(b"content-length:"):
            lines[i] = b"Content-Length: " + str(n).encode()
    return b"\r\n".join(lines)


def _corrupt_response(raw: bytes, fault: Fault) -> bytes:
    """Apply one wrong-data fault to a fully buffered HTTP response."""
    head, sep, body = raw.partition(b"\r\n\r\n")
    if not sep or not body:
        return raw  # nothing corruptible; deliver as-is
    if fault.kind == BITFLIP:
        off = fault.after_bytes or len(body) // 2
        off = min(max(off, 0), len(body) - 1)
        body = body[:off] + bytes([body[off] ^ 0x01]) + body[off + 1 :]
    elif fault.kind == TRUNCATE_BODY:
        keep = fault.after_bytes or len(body) // 2
        body = body[: max(keep, 0)]
    elif fault.kind == GARBAGE_HEADER:
        n = min(len(body), 64)
        body = b"\xff" * n + body[n:]
    return _set_content_length(head, len(body)) + sep + body


def _rst_close(sock: socket.socket):
    """Close with RST (SO_LINGER 0): the peer sees ECONNRESET, not FIN —
    the signature of a crashed process, which is what we are simulating."""
    try:
        sock.setsockopt(
            socket.SOL_SOCKET, socket.SO_LINGER, struct.pack("ii", 1, 0)
        )
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class ChaosProxy:
    """TCP proxy fronting one real backend, injecting `FaultPlan` faults.

    Thread-per-connection like the gateway itself; `start()` returns once
    the listener is accepting (`self.port` is bound either way). `stop()`
    tears everything down; `down()`/`up()` simulate the whole host
    vanishing and returning (connections are REFUSED while down — the one
    failure mode an accepting socket cannot fake)."""

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        plan: FaultPlan | None = None,
        port: int = 0,
        host: str = "127.0.0.1",
    ):
        self.upstream = (upstream_host, upstream_port)
        self.plan = plan or FaultPlan()
        self.host = host
        self._requested_port = port
        self.port = 0
        self.conn_count = 0  # accept counter = the FaultPlan index
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._down = threading.Event()
        self._listener: socket.socket | None = None
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def _bind(self) -> socket.socket:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self._requested_port or self.port))
        srv.listen(64)
        srv.settimeout(0.1)
        return srv

    def start(self) -> "ChaosProxy":
        self._listener = self._bind()
        self.port = self._listener.getsockname()[1]
        self._thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=f"chaos:{self.port}"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def down(self):
        """Simulate the host vanishing: close the listener so new
        connections get ECONNREFUSED (nothing is listening)."""
        self._down.set()

    def up(self):
        """Bring the host back on the same port."""
        self._down.clear()

    # -- accept loop --------------------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            if self._down.is_set():
                if self._listener is not None:
                    try:
                        self._listener.close()
                    except OSError:
                        pass
                    self._listener = None
                time.sleep(0.02)
                continue
            if self._listener is None:
                try:
                    self._listener = self._bind()
                except OSError:
                    time.sleep(0.05)
                    continue
            try:
                client, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                continue
            with self._lock:
                idx = self.conn_count
                self.conn_count += 1
                fault = self.plan.fault_for(idx)
            threading.Thread(
                target=self._handle, args=(client, fault), daemon=True
            ).start()

    # -- per-connection fault execution -------------------------------------

    def _handle(self, client: socket.socket, fault: Fault):
        try:
            if fault.kind == REFUSE:
                _rst_close(client)
                return
            if fault.kind == LATENCY:
                time.sleep(fault.delay_s)
            request = _read_request(client)
            if not request:
                client.close()
                return
            if fault.kind == RESET_ON_ACCEPT:
                _rst_close(client)
                return
            if fault.kind == STALL:
                # slow-loris: hold the line silent, then die. An interrupted
                # wait (proxy stopped) still RSTs so nothing leaks.
                self._stop.wait(fault.delay_s)
                _rst_close(client)
                return
            self._proxy(client, request, fault)
        except OSError:
            try:
                client.close()
            except OSError:
                pass

    def _proxy(self, client: socket.socket, request: bytes, fault: Fault):
        if fault.kind in _CORRUPT_KINDS:
            self._proxy_corrupt(client, request, fault)
            return
        budget = fault.after_bytes if fault.kind == MIDSTREAM_RESET else None
        sent = 0
        try:
            with socket.create_connection(self.upstream, timeout=10) as upstream:
                upstream.sendall(request)
                upstream.settimeout(60)
                while True:
                    chunk = upstream.recv(16384)
                    if not chunk:
                        break
                    if budget is not None and sent + len(chunk) >= budget:
                        client.sendall(chunk[: max(0, budget - sent)])
                        _rst_close(client)
                        return
                    client.sendall(chunk)
                    sent += len(chunk)
        except OSError:
            pass
        try:
            client.close()
        except OSError:
            pass

    def _proxy_corrupt(self, client: socket.socket, request: bytes, fault: Fault):
        """Buffer the full upstream response, mangle it, deliver it whole:
        the client sees a CLEAN transport carrying WRONG bytes."""
        chunks = []
        try:
            with socket.create_connection(self.upstream, timeout=10) as upstream:
                upstream.sendall(request)
                upstream.settimeout(60)
                while True:
                    chunk = upstream.recv(16384)
                    if not chunk:
                        break
                    chunks.append(chunk)
            client.sendall(_corrupt_response(b"".join(chunks), fault))
        except OSError:
            pass
        try:
            client.close()
        except OSError:
            pass
