"""Goodput-driven autoscaler: the capacity half of the fleet control plane.

The scheduler (server/scheduler.py) decides *who* runs on a replica; this
module decides *how many replicas run*. It closes ROADMAP item 3's loop:
the PR 9 fleet signal plane already measures per-replica goodput, batcher
occupancy, shed rates, and SLO attainment — the autoscaler watches those
signals on a tick and actuates through the SAME drain path the operator's
``POST /gateway/drain`` endpoints use (Balancer.set_draining), so a human
and the control loop can never disagree about what "drained" means.

Policy per tick (:meth:`Autoscaler.tick`, manually drivable in tests):

* **pressure** — any fresh live replica sheds, queues, or misses its TTFT
  SLO target → **undrain** a drained replica (scale up), instantly: adding
  capacity is cheap and reversible;
* **headroom** — fleet utilization (active batch slots / total slots over
  fresh, non-draining replicas) below the low watermark for
  ``down_after`` CONSECUTIVE ticks (one quiet scrape must not shrink the
  fleet) and more than ``min_live`` replicas live → **drain** the replica
  contributing the least goodput (scale down);
* otherwise **hold**.

Draining is where the *warm handoff* lands: before ``set_draining``, the
autoscaler fetches the victim's ``GET /debug/hot_prefixes`` snapshot (the
replica-side HotPrefixTracker's router-compatible chain keys) and re-homes
those chains' affinity onto surviving rendezvous owners
(Router.rehome_keys) — so the fleet's shared-prefix traffic re-concentrates
on ONE new home per chain *before* the old home stops taking requests,
instead of spraying cold prefills across the fleet when it disappears.
Inflight requests on the drained replica finish normally (draining only
stops NEW assignments) — zero failed requests by construction.

Every decision is counted (``dlt_autoscaler_decisions_total{action=...}``,
``dlt_autoscaler_handoff_keys_total``) and summarized in the
``autoscaler`` section of ``GET /gateway/fleet``. Deliberately
stdlib-only, like the rest of the gateway.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass

#: every action ``dlt_autoscaler_decisions_total`` is labeled with —
#: ``follower_hold`` is the peered-gateway case (server/peering.py):
#: exactly one gateway (the lowest live peer id) runs the control loop;
#: the others count held ticks here so a silent leader is visible
ACTIONS = ("drain", "undrain", "hold", "follower_hold")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


@dataclass
class AutoscalerConfig:
    """Autoscaler knobs (``DLT_AUTOSCALE_*`` envs; the gateway's
    ``--autoscale-s`` flag sets the cadence)."""

    interval_s: float = 0.0     # tick cadence; <= 0 disables the thread
    min_live: int = 1           # never drain below this many live replicas
    low_water: float = 0.30     # utilization below this = shrink candidate
    down_after: int = 3         # consecutive low ticks before a drain
    cooldown_s: float = 30.0    # quiet period after any scale action
    slo_target: float = 0.90    # TTFT attainment below this = pressure
    handoff_top_n: int = 64     # hot chains fetched from a drain victim
    handoff_timeout_s: float = 2.0

    @classmethod
    def resolve(cls, interval_s: float | None = None) -> "AutoscalerConfig":
        return cls(
            interval_s=(
                _env_float("DLT_AUTOSCALE_S", 0.0)
                if interval_s is None
                else interval_s
            ),
            min_live=int(_env_float("DLT_AUTOSCALE_MIN_LIVE", 1)),
            low_water=_env_float("DLT_AUTOSCALE_LOW", 0.30),
            down_after=int(_env_float("DLT_AUTOSCALE_DOWN_AFTER", 3)),
            cooldown_s=_env_float("DLT_AUTOSCALE_COOLDOWN_S", 30.0),
            slo_target=_env_float("DLT_AUTOSCALE_SLO_TARGET", 0.90),
            handoff_top_n=int(_env_float("DLT_AUTOSCALE_HANDOFF_N", 64)),
            handoff_timeout_s=_env_float("DLT_AUTOSCALE_HANDOFF_TIMEOUT_S", 2.0),
        )

    def snapshot(self) -> dict:
        return {
            "interval_s": self.interval_s,
            "min_live": self.min_live,
            "low_water": self.low_water,
            "down_after": self.down_after,
            "cooldown_s": self.cooldown_s,
            "slo_target": self.slo_target,
            "handoff_top_n": self.handoff_top_n,
        }


class Autoscaler:
    """The gateway's capacity control loop over a Balancer (+ its attached
    FleetScraper and Router). Construct and call :meth:`tick` directly in
    tests; :meth:`start` runs the background loop."""

    def __init__(self, balancer, interval_s: float | None = None,
                 config: AutoscalerConfig | None = None):
        self.balancer = balancer
        self.config = config or AutoscalerConfig.resolve(interval_s)
        self.interval_s = self.config.interval_s
        self._lock = threading.Lock()
        self.decisions = {a: 0 for a in ACTIONS}
        self.handoff_keys = 0
        self.ticks = 0
        self.last: dict = {}
        self._low_ticks = 0
        self._cooldown_until = 0.0
        # keys THIS loop drained: the undrain arm only ever re-admits
        # these — a replica an operator drained via POST /gateway/drain
        # (for an upgrade, say) must never be undrained by a shed spike
        self._drained_by_me: set = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Autoscaler":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gateway-autoscaler"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # the control loop must never die mid-incident: a failed
                # tick is a held tick, visible as the hold count + a
                # last-decision gap, retried next interval
                with self._lock:
                    self.decisions["hold"] += 1

    # -- the loop body -------------------------------------------------------

    def _fleet_view(self):
        """Join the balancer's backend state with the scraper's fresh
        signals: ``[(key, draining, signals|None)]`` — signals None when
        stale/never-scraped (a silent replica contributes no utilization
        evidence, so it can neither justify nor block a scale decision)."""
        fleet = getattr(self.balancer, "fleet", None)
        rows = fleet.router_signals() if fleet is not None else {}
        with self.balancer.lock:
            backends = [
                (b.key, b.draining) for b in self.balancer.config.backends
            ]
        out = []
        for key, draining in backends:
            row = rows.get(key) or {}
            fresh = not row.get("stale", True)
            out.append((key, draining, row.get("signals") if fresh else None))
        return out

    @staticmethod
    def _utilization(fresh_live) -> float | None:
        """Active-slot fraction over the fresh live replicas (None with no
        evidence). Queue depth counts as demand beyond capacity: a full
        replica with a backlog reads >1 busy, not exactly-full."""
        total = active = 0.0
        for _, sig in fresh_live:
            slots = sig.get("batcher_batch_slots") or 0
            if slots <= 0:
                continue
            total += slots
            active += min(sig.get("batcher_slots_active", 0), slots)
            active += sig.get("batcher_queue_depth", 0)
        if total <= 0:
            return None
        return active / total

    def _pressure(self, fresh_live) -> str | None:
        """The scale-up signal: shedding, queued demand, or a missed TTFT
        SLO on any fresh live replica. Per-class attainment rows (the
        fleet table's slo_ttft_attainment_by_class, where a replica
        reports them) are checked class by class — a batch-heavy fleet's
        healthy aggregate must not mask an interactive-class SLO miss.
        Returns the reason or None."""
        for key, sig in fresh_live:
            if sig.get("shed_per_s", 0) > 0:
                return f"shed:{key}"
            if sig.get("batcher_queue_depth", 0) > 0:
                return f"queue:{key}"
            by_class = sig.get("slo_ttft_attainment_by_class") or {}
            for klass, att in by_class.items():
                if att < self.config.slo_target:
                    return f"slo:{klass}:{key}"
            att = sig.get("slo_ttft_attainment")
            if att is not None and att < self.config.slo_target:
                return f"slo:{key}"
        return None

    def _drain_victim(self, fresh_live) -> str:
        """Whom to drain: the fresh live replica contributing the least
        goodput (ties: least prefix reuse — its cache is the cheapest to
        lose — then the later backend)."""
        return min(
            fresh_live,
            key=lambda t: (
                t[1].get("goodput_tokens_per_s", 0.0),
                t[1].get("prefix_hit_tokens_per_s", 0.0),
            ),
        )[0]

    def _warm_handoff(self, victim_key: str, remaining_keys) -> int:
        """Fetch the victim's hottest chain keys and re-home their
        affinity onto surviving rendezvous owners BEFORE the drain lands.
        Best-effort: a replica that cannot answer just drains cold (the
        set_draining hook still purges/re-homes the learned map)."""
        router = getattr(self.balancer, "router", None)
        if router is None or not remaining_keys:
            return 0
        backend = None
        with self.balancer.lock:
            for b in self.balancer.config.backends:
                if b.key == victim_key:
                    backend = (b.host, b.port)
                    break
        if backend is None:
            return 0
        from .fleet import http_get_text
        import json

        try:
            status, body = http_get_text(
                backend[0], backend[1],
                f"/debug/hot_prefixes?n={self.config.handoff_top_n}",
                self.config.handoff_timeout_s,
            )
            if status != 200:
                return 0
            chains = json.loads(body).get("chains", [])
        except Exception:
            return 0
        # size-aware ranking: re-home the chains that are both hot AND
        # expensive to recompute first — hits x stored bytes (the snapshot's
        # `bytes` is stored-width, so int8 caches rank by real footprint).
        # Chains without size info (never completed) fall back to hits-only.
        chains = sorted(
            (c for c in chains if isinstance(c, dict)),
            key=lambda c: (
                c.get("hits", 0) * (1 + c.get("bytes", 0)), c.get("hits", 0)
            ),
            reverse=True,
        )
        keys = [c.get("key") for c in chains]
        n = router.rehome_keys(
            [k for k in keys if k], remaining_keys, from_key=victim_key
        )
        with self._lock:
            self.handoff_keys += n
        return n

    def drain(self, victim_key: str) -> dict:
        """Warm-handoff + drain one replica (the tick's scale-down arm;
        public so chaos tests can force the exact decision)."""
        with self.balancer.lock:
            remaining = [
                b.key for b in self.balancer.config.backends
                if not b.draining and b.key != victim_key
            ]
        rehomed = self._warm_handoff(victim_key, remaining)
        self.balancer.set_draining(victim_key, True, by="autoscaler")
        with self._lock:
            self._drained_by_me.add(victim_key)
        return {"victim": victim_key, "rehomed_keys": rehomed}

    def forget(self, key: str):
        """Drop ownership of a drain: called by Balancer.set_draining on
        ANY undrain (operator or loop) — once a replica has been undrained
        by anyone, a later drain of it is not ours to revert."""
        with self._lock:
            self._drained_by_me.discard(key)

    def adopt_drain(self, key: str):
        """Take ownership of a drain this instance did NOT perform: a
        warm-restarting gateway re-learning ``by=autoscaler`` drain hints
        from replica /health (server/recovery.py), or a follower applying
        a leader's drain event (server/peering.py) — either way the
        control loop must be able to undrain it on pressure, or the
        replica is stranded drained forever."""
        with self._lock:
            self._drained_by_me.add(key)

    def tick(self) -> dict:
        """One control-loop evaluation. Returns (and remembers) the
        decision record; never raises through the loop."""
        cfg = self.config
        now = time.monotonic()
        # peered gateways elect exactly ONE autoscaler leader (lowest
        # live peer id, server/peering.py): followers hold their ticks —
        # two control loops draining independently would double-shrink
        # the fleet, and their cooldown/low-tick state would diverge
        peering = getattr(self.balancer, "peering", None)
        if peering is not None and not peering.is_leader():
            record = {
                "action": "follower_hold",
                "detail": f"leader={peering.leader_id()}",
                "utilization": None, "pressure": None,
                "live": 0, "drained": 0, "low_ticks": self._low_ticks,
            }
            with self._lock:
                self.decisions["follower_hold"] += 1
                self.ticks += 1
                self.last = record
            return record
        view = self._fleet_view()
        live = [(k, s) for k, d, s in view if not d]
        drained = [k for k, d, _ in view if d]
        fresh_live = [(k, s) for k, s in live if s is not None]
        util = self._utilization(fresh_live)
        pressure = self._pressure(fresh_live)
        # only replicas THIS loop drained are undrain candidates — an
        # operator's drain (upgrade, debugging) is not ours to revert
        with self._lock:
            own_drained = [k for k in drained if k in self._drained_by_me]
        action, detail = "hold", ""
        if pressure and own_drained:
            # scale up: re-admit a drained replica. Cooldown does NOT
            # gate this arm — pressure is user-visible pain and adding
            # capacity back is safe; flap damping lives on the drain arm.
            target = own_drained[0]
            # set_draining's undrain hook calls our forget(target), so the
            # ownership entry clears on the same path an operator's would
            self.balancer.set_draining(target, False)
            action, detail = "undrain", f"{target} ({pressure})"
            self._low_ticks = 0
            self._cooldown_until = now + cfg.cooldown_s
        elif (
            pressure is None  # NEVER shrink while any replica sheds,
            # queues, or misses its SLO — even if raw utilization is low
            and util is not None
            and util < cfg.low_water
            # min_live counts replicas with FRESH evidence: a crashed or
            # silent backend is not capacity, and counting it could drain
            # the last actually-working replica during a partial outage
            and len(fresh_live) > cfg.min_live
            and now >= self._cooldown_until
        ):
            self._low_ticks += 1
            if self._low_ticks >= cfg.down_after and fresh_live:
                victim = self._drain_victim(fresh_live)
                res = self.drain(victim)
                action = "drain"
                detail = f"{victim} (rehomed {res['rehomed_keys']} keys)"
                self._low_ticks = 0
                self._cooldown_until = now + cfg.cooldown_s
        else:
            self._low_ticks = 0
        record = {
            "action": action,
            "detail": detail,
            "utilization": None if util is None else round(util, 3),
            "pressure": pressure,
            "live": len(live),
            "drained": len(drained),
            "low_ticks": self._low_ticks,
        }
        with self._lock:
            self.decisions[action] += 1
            self.ticks += 1
            self.last = record
        return record

    # -- views ---------------------------------------------------------------

    def metrics_lines(self) -> list:
        from ..runtime.tracing import prom_line  # stdlib-only module

        with self._lock:
            decisions = dict(self.decisions)
            handoff = self.handoff_keys
            last = dict(self.last)
        lines = ["# TYPE dlt_autoscaler_decisions_total counter"]
        for a in ACTIONS:
            lines.append(
                prom_line(
                    "dlt_autoscaler_decisions_total", {"action": a},
                    decisions.get(a, 0),
                )
            )
        lines.append("# TYPE dlt_autoscaler_handoff_keys_total counter")
        lines.append(prom_line("dlt_autoscaler_handoff_keys_total", None, handoff))
        if last.get("utilization") is not None:
            lines.append("# TYPE dlt_autoscaler_utilization gauge")
            lines.append(
                prom_line("dlt_autoscaler_utilization", None, last["utilization"])
            )
        return lines

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "config": self.config.snapshot(),
                "decisions": dict(self.decisions),
                "handoff_keys": self.handoff_keys,
                "ticks": self.ticks,
                "last": dict(self.last),
            }
