"""SLO-class scheduling: admission quotas, queue priorities, and
preemption policy for the serving tier.

PRs 9-10 built the *sensors* (per-replica signal table, goodput ledger,
SLO attainment) and one *actuator* (cache-aware routing); this module is
the policy half of the control plane that closes the loop. The Batcher
(server/api.py) already knows *how* to park and shed — pool-exhaustion
park/shed, ``max_backlog`` 503s — but treated every request identically.
Real fleets don't: an interactive chat turn, a standard API call, and an
overnight batch job have different latency contracts, and under pressure
the scheduler must know *whom* to delay, shed, or preempt.

Three SLO classes, requested per call (``slo_class`` in the ``/v1/chat``
body, or the ``X-DLT-SLO-Class`` header — which the gateway forwards
byte-transparently, so one client header rides retries and routing):

* ``interactive`` — tightest TTFT contract; admitted first, never the
  preferred shed victim;
* ``standard``    — the default; the pre-SLO-class behavior;
* ``batch``       — throughput traffic; capped backlog share (admission
  quota), first in line for shedding, and preemptible by waiting
  interactive traffic.

The policy core here is deliberately **engine-independent and
stdlib-only**: the real Batcher drives it against live engines, and the
fleet load twin (server/loadtwin.py) drives the SAME code against stub
replicas — so scheduler changes are CI-testable at 10-50-replica scale
without TPUs.

Every decision is counted by ``(class, action)`` and exported as
``dlt_scheduler_decisions_total{class=...,action=...}`` on ``/metrics``
(zero-valued combinations always render), mirrored as batch-timeline
marks, and reflected per class in the goodput ledger
(``dlt_goodput_tokens_per_s{slo_class=...}``,
``dlt_wasted_tokens_total{reason=...,slo_class=...}``).
"""

from __future__ import annotations

import collections
import os
import threading

#: priority order: earlier = higher priority (admitted first, shed last)
SLO_CLASSES = ("interactive", "standard", "batch")
DEFAULT_CLASS = "standard"
#: rank by class name; lower rank = higher priority
CLASS_RANK = {c: i for i, c in enumerate(SLO_CLASSES)}

#: request header carrying the class end-to-end (the gateway forwards all
#: client headers byte-transparently, retries included)
SLO_CLASS_HEADER = "X-DLT-SLO-Class"

#: end-to-end deadline header: milliseconds of budget remaining, minted at
#: the gateway (client header or the class default below) and re-stamped
#: with the REMAINING budget on every retry attempt — so the deadline is
#: one clock across routing, retries, and the replica's Batcher, without
#: ever shipping an absolute timestamp between unsynchronized hosts
DEADLINE_HEADER = "X-DLT-Deadline-Ms"

#: class scaling applied to DLT_DEFAULT_DEADLINE_MS when no per-class env
#: overrides: an interactive request's answer is worthless sooner than a
#: batch job's — the deadline composes with the SLO class, it doesn't
#: flatten it
DEADLINE_CLASS_SCALE = {"interactive": 0.5, "standard": 1.0, "batch": 4.0}

#: every env that can mint a deadline WITHOUT a client header — the
#: gateway checks these to skip chat-body parsing entirely when no
#: consumer (router, quarantine, deadline) is enabled
DEADLINE_ENVS = ("DLT_DEFAULT_DEADLINE_MS",) + tuple(
    f"DLT_DEADLINE_MS_{c.upper()}" for c in SLO_CLASSES
)


def resolve_deadline_ms(klass: str, client_value=None) -> int:
    """The deadline budget (ms) one request rides with; 0 = no deadline
    (the default — deadlines are opt-in via the client header or
    ``DLT_DEFAULT_DEADLINE_MS``). Resolution order: the client's own
    header (clamped positive), then ``DLT_DEADLINE_MS_<CLASS>``, then
    ``DLT_DEFAULT_DEADLINE_MS`` scaled by the class's
    :data:`DEADLINE_CLASS_SCALE` factor."""
    if client_value is not None:
        try:
            ms = int(float(client_value))
            if ms > 0:
                return ms
        except (TypeError, ValueError):
            pass  # a garbage header degrades to the configured default,
            # never fails the request (the resolve_slo_class discipline)
    klass = resolve_slo_class(klass)
    per_class = os.environ.get(f"DLT_DEADLINE_MS_{klass.upper()}")
    if per_class is not None:
        try:
            return max(int(float(per_class)), 0)
        except ValueError:
            pass
    default = _env_float("DLT_DEFAULT_DEADLINE_MS", 0.0)
    if default <= 0:
        return 0
    return max(int(default * DEADLINE_CLASS_SCALE.get(klass, 1.0)), 1)

#: every action ``dlt_scheduler_decisions_total`` is labeled with:
#: * ``admit``        — a request entered a batch slot;
#: * ``shed_backlog`` — turned away at admission (total backlog cap or the
#:                      class's quota share exceeded) with 503+Retry-After;
#: * ``shed_pool``    — an in-flight row shed under KV page-pool pressure;
#: * ``preempt``      — an in-flight lower-class row evicted so a waiting
#:                      higher-class request could take its slot;
#: * ``park``         — an admission parked on pool pressure (will retry).
SCHED_ACTIONS = ("admit", "shed_backlog", "shed_pool", "preempt", "park")


def resolve_slo_class(raw) -> str:
    """Normalize a requested class (header or body value); anything
    unknown — or absent — is ``standard``: a typo'd class must degrade to
    the default contract, never fail the request or grant priority."""
    if isinstance(raw, str):
        k = raw.strip().lower()
        if k in CLASS_RANK:
            return k
    return DEFAULT_CLASS


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class SchedulerConfig:
    """Per-class admission quotas (share of ``max_backlog`` a class may
    occupy, ``DLT_SLO_QUOTA_<CLASS>``) and the preemption switch
    (``DLT_SLO_PREEMPT``, default on). Defaults: interactive and standard
    may fill the whole backlog; batch is capped at half of it, so a batch
    flood can never consume the queue ahead of latency-bound traffic."""

    def __init__(self, quotas: dict | None = None, preempt: bool | None = None):
        base = {"interactive": 1.0, "standard": 1.0, "batch": 0.5}
        for c in SLO_CLASSES:
            base[c] = _env_float(f"DLT_SLO_QUOTA_{c.upper()}", base[c])
        if quotas:
            base.update(quotas)
        self.quotas = {c: max(0.0, min(1.0, base[c])) for c in SLO_CLASSES}
        if preempt is None:
            preempt = os.environ.get("DLT_SLO_PREEMPT", "1") not in ("0", "")
        self.preempt = bool(preempt)

    def snapshot(self) -> dict:
        return {"quotas": dict(self.quotas), "preempt": self.preempt}


class ClassQueues:
    """Per-class FIFO backlog with priority pop: interactive drains before
    standard drains before batch; within a class, arrival order holds.
    Thread-compat with the old plain deque: ``len()``/truthiness are the
    total depth, so existing ``queue_depth`` readers keep working."""

    def __init__(self):
        self._q = {c: collections.deque() for c in SLO_CLASSES}

    def append(self, item, klass: str = DEFAULT_CLASS):
        self._q[resolve_slo_class(klass)].append(item)

    def popleft(self):
        """Highest-priority non-empty class's oldest item."""
        for c in SLO_CLASSES:
            if self._q[c]:
                return self._q[c].popleft()
        raise IndexError("pop from empty ClassQueues")

    def peek_class(self) -> str | None:
        """Class of the item ``popleft`` would return (None when empty)."""
        for c in SLO_CLASSES:
            if self._q[c]:
                return c
        return None

    def remove(self, item, klass: str = DEFAULT_CLASS) -> None:
        """Withdraw a queued item (a waiter that timed out or died) —
        raises ValueError when absent, like deque.remove."""
        self._q[resolve_slo_class(klass)].remove(item)

    def depth(self, klass: str) -> int:
        return len(self._q[resolve_slo_class(klass)])

    def depths(self) -> dict:
        return {c: len(self._q[c]) for c in SLO_CLASSES}

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def __iter__(self):
        for c in SLO_CLASSES:
            yield from self._q[c]


class SloScheduler:
    """The per-replica scheduling policy + decision counters. One instance
    per Batcher (and per stub replica in the load twin); every method is a
    host-side dict/deque touch — nothing here goes near the device."""

    def __init__(self, config: SchedulerConfig | None = None):
        self.config = config or SchedulerConfig()
        self._lock = threading.Lock()
        self.decisions = {
            (c, a): 0 for c in SLO_CLASSES for a in SCHED_ACTIONS
        }

    # -- decisions -----------------------------------------------------------

    def record(self, klass: str, action: str, n: int = 1):
        key = (resolve_slo_class(klass), action)
        with self._lock:
            self.decisions[key] = self.decisions.get(key, 0) + n

    def decisions_series(self) -> list:
        """``[(labels, value), ...]`` for the labeled counter family —
        every (class, action) combination present, zeros included, so
        dashboards never see a series appear from nowhere mid-incident."""
        with self._lock:
            d = dict(self.decisions)
        return [
            ({"class": c, "action": a}, d.get((c, a), 0))
            for c in SLO_CLASSES
            for a in SCHED_ACTIONS
        ]

    def decisions_snapshot(self) -> dict:
        with self._lock:
            return {f"{c}:{a}": v for (c, a), v in self.decisions.items() if v}

    # -- admission -----------------------------------------------------------

    def admission_allowed(self, klass: str, queues: ClassQueues,
                          max_backlog: int, extra_depth: int = 0) -> bool:
        """May a new ``klass`` request join the backlog? False on the total
        cap (the pre-class behavior) OR on the class's own quota share —
        a batch flood saturating its share must not consume queue slots
        latency-bound classes would have used. ``extra_depth`` counts this
        class's accepted-but-not-yet-queued submissions (the Batcher's
        self.q race window), so a concurrent burst cannot slip past the
        quota before the loop drains it."""
        klass = resolve_slo_class(klass)
        if len(queues) + extra_depth >= max_backlog:
            return False
        cap = self.config.quotas[klass] * max_backlog
        if cap <= 0:
            return False  # quota 0 means BLOCKED, not one-in-flight —
            # the operator's kill switch for a class during an incident
        return queues.depth(klass) + extra_depth < max(cap, 1)

    # -- victim selection ----------------------------------------------------

    @staticmethod
    def shed_victim(rows) -> int:
        """Whom to shed under pool pressure: ``rows`` is a non-empty list
        of ``(row, klass, progress_tokens)``; returns the chosen row.
        Policy: LOWEST class first (batch before standard before
        interactive), then LEAST progress (the cheapest work to discard),
        then the highest row index (matches the old ``-r`` tiebreak)."""
        return min(
            rows,
            key=lambda t: (-CLASS_RANK.get(t[1], CLASS_RANK[DEFAULT_CLASS]),
                           t[2], -t[0]),
        )[0]

    def preempt_victim(self, waiting_klass: str, rows):
        """Whom to preempt so a waiting ``waiting_klass`` request can take
        a slot: the lowest-class least-progress row whose class is STRICTLY
        below the waiter's (standard never preempts standard; preemption
        off disables entirely). Returns a row index or None."""
        if not self.config.preempt or not rows:
            return None
        wrank = CLASS_RANK.get(resolve_slo_class(waiting_klass), 1)
        eligible = [
            t for t in rows
            if CLASS_RANK.get(t[1], CLASS_RANK[DEFAULT_CLASS]) > wrank
        ]
        if not eligible:
            return None
        return self.shed_victim(eligible)

    def snapshot(self) -> dict:
        return {
            "config": self.config.snapshot(),
            "decisions": self.decisions_snapshot(),
        }


class HotPrefixTracker:
    """Bounded hit counts over the router's chained prefix keys — the
    replica-side half of the **warm drain handoff**: the gateway's
    autoscaler fetches ``GET /debug/hot_prefixes`` from a replica it is
    about to drain and re-homes the listed chains' affinity BEFORE the
    replica disappears, so shared-prefix traffic concentrates on ONE new
    home instead of spraying cold across the fleet.

    The keys are the SAME 64-char-block FNV-1a chain hashes the router's
    locality map learns (server/router.py ``prefix_chain``), computed
    replica-side over the chat messages text — so the snapshot's keys are
    directly re-homeable without any token-to-text mapping. Bounded LRU;
    one lock hold per request (never per token).

    Each chain also carries the KV footprint it resolves to — pages and
    STORED-WIDTH bytes (int8-quantized caches report quantized bytes, not
    the compute-dtype size), attached by the completion path once the
    prompt is tokenized (``note_size``). The autoscaler's warm handoff
    ranks on hits x bytes: a chain that is both hot and expensive to
    recompute is the one worth re-homing first."""

    def __init__(self, size: int = 4096):
        self.size = size
        self._lock = threading.Lock()
        # key -> [hits, pages, nbytes]; pages/nbytes are the largest
        # footprint seen (depths share keys; max is what a re-home moves)
        self._hits: "collections.OrderedDict[int, list]" = (
            collections.OrderedDict()
        )

    def record(self, chain) -> None:
        """Count one request's chain keys (all depths: the locality map
        holds every depth, so every depth must be re-homeable)."""
        if not chain:
            return
        with self._lock:
            for ck in chain:
                ent = self._hits.get(ck)
                if ent is None:
                    ent = self._hits[ck] = [0, 0, 0]
                ent[0] += 1
                self._hits.move_to_end(ck)
            while len(self._hits) > self.size:
                self._hits.popitem(last=False)

    def note_size(self, chain, pages: int, nbytes: int) -> None:
        """Attach the cacheable-prefix footprint to a request's chain keys
        (hits untouched — ``record`` already counted this request). Called
        by the completion path, which knows the tokenized prefix boundary
        and the cache's stored-width byte cost."""
        if not chain or (pages <= 0 and nbytes <= 0):
            return
        with self._lock:
            for ck in chain:
                ent = self._hits.get(ck)
                if ent is None:
                    continue  # evicted (or never recorded): don't resurrect
                ent[1] = max(ent[1], pages)
                ent[2] = max(ent[2], nbytes)

    def snapshot(self, top_n: int = 64) -> dict:
        """The ``/debug/hot_prefixes`` payload: the hottest chain keys as
        zero-padded hex (the handoff wire format), hit-count descending
        with stored bytes as the tiebreak, each with its KV footprint."""
        with self._lock:
            items = sorted(
                self._hits.items(),
                key=lambda kv: (kv[1][0], kv[1][2]), reverse=True,
            )[:top_n]
            n = len(self._hits)
        return {
            "n_tracked": n,
            "chains": [
                {
                    "key": f"{ck:016x}", "hits": hits,
                    "pages": pages, "bytes": nbytes,
                }
                for ck, (hits, pages, nbytes) in items
            ],
        }
