"""Prefill/decode disaggregation: dedicated prefill workers ship KV.

TTFT-heavy and decode-heavy traffic contend for the same chips on a unified
replica: one long prompt's prefill chunks interleave with — and bound the
latency of — every co-batched decode stream. DistServe's answer (and ours)
is to split the roles: **prefill workers** run prompts and ship the finished
KV; **decode workers** splice it and stream tokens. Since the KV movement
layer landed (runtime/kv_transport.py), the split composes with every KV
subsystem instead of excluding them:

* the prefill worker runs an ordinary ``engine.prefill`` over the prompt's
  leading ``P`` tokens (``P`` = the prefix cache's bucket_down boundary) and
  extracts the slice on ITS layout — contiguous workers through the warmed
  ``prefix_extract`` program, PAGED workers by gathering their pool pages
  (``page_extract``) — into the one ``[L, n, h, d]`` shape both the wire
  codec and the device transport speak;
* **content-addressed page skip**: the decode worker names the leading
  pages it already holds by their chained token-content hashes
  (:func:`~..runtime.kv_transport.page_keys`) and the worker ships only the
  rest — repeated/growing prefixes move only their missing pages
  (``disagg_pages_skipped``), and a paged entry's identity on the wire is
  its content, never a pool-local page id;
* **transport per peer** (``DLT_KV_TRANSPORT`` = auto|device|http): same-
  process peers (and, on pods, jax-addressable devices) move KV as device
  arrays with zero host serialization (:class:`DeviceKvTransport`); the
  PR 10 length-prefixed binary codec stays as the portable HTTP fallback.
  Per-path walls and bytes land in ``kv_transfer_us[{path}]`` /
  ``kv_transfer_bytes_{path}`` — the ≥3x device-vs-http cut is the bench
  bar (bench.py leg_kv_movement);
* the decode worker inserts the shipped slice into its radix prefix cache
  (:meth:`~..runtime.prefix_cache.PrefixCache.insert_external` — paged
  engines scatter into freshly allocated pool pages and retain the held
  base pages), and the request then takes the UNMODIFIED admission path —
  match, pin, splice, resume — which is what makes disaggregated output
  bit-identical to unified serving. The insert itself is DEFERRED to the
  engine's dispatch thread (:class:`PendingExternalKv`): a paged insert
  donates the live pool, which a handler thread must never race;
* **degradation, not failure**: a prefill worker dying mid-transfer (the
  chaos suite kills one mid-KV-body; the device path has its own injection
  hook) leaves the decode worker exactly one request-local consequence —
  no cache entry — so the request cold-prefills locally and completes
  token-identical. The event is counted (``disagg_degraded``), ledgered
  (``dlt_wasted_tokens_total{reason=transfer_retry}``), and traced (a
  ``kv_transfer`` event with ``failed=1`` lands even on unsampled traces).

Roles are picked with ``--role {prefill,decode,unified}`` (``DLT_ROLE``) on
the API server; decode workers name their peers with ``--prefill-peer
host:port`` (repeatable; ``DLT_PREFILL_PEER`` comma-separated). Both roles
now serve EITHER KV layout — the paged-pool default included.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np

# the wire codec lives with the rest of the KV movement layer now; these
# re-exports keep the PR 10 import surface working
from ..runtime.kv_transport import (  # noqa: F401 — re-exported API
    KEY_PAGE_TOKENS,
    WIRE_VERSION,
    KvCodecError,
    KvIntegrityError,
    KvVersionError,
    TransferResult,
    build_transports,
    doubling_segments,
    kv_payload,
    matching_pages,
    page_keys,
    parse_kv_payload,
    resolve_transport,
    segment_checksum,
    transport_for,
    verify_transfer,
)

ROLES = ("unified", "prefill", "decode")

#: decode->prefill-worker round-trip budget (connect + prefill + transfer);
#: generous because the worker's wall includes real prefill compute
DEFAULT_TIMEOUT_S = 30.0


def resolve_role(explicit=None) -> str:
    """``--role`` flag > ``DLT_ROLE`` env > unified. Unknown values raise:
    a typo'd role silently serving unified would defeat the topology."""
    role = explicit or os.environ.get("DLT_ROLE") or "unified"
    if role not in ROLES:
        raise ValueError(f"unknown serving role {role!r} (one of {ROLES})")
    return role


def resolve_peers(explicit=None) -> list:
    """``--prefill-peer`` (repeatable) > ``DLT_PREFILL_PEER`` (comma-
    separated) > none. Returns ``[(host, port), ...]``."""
    raw = list(explicit) if explicit else [
        s for s in os.environ.get("DLT_PREFILL_PEER", "").split(",") if s.strip()
    ]
    peers = []
    for s in raw:
        host, _, port = s.strip().rpartition(":")
        peers.append((host or "127.0.0.1", int(port)))
    return peers


# -- the prefill-worker side --------------------------------------------------


def prefill_boundary(n_prompt_tokens: int, seq_len: int) -> int:
    """The bucket boundary a disaggregated transfer covers: the largest
    prefix bucket <= the prompt's prefillable span (the last prompt token is
    fed at decode time, exactly like the local publish cap). 0 = the prompt
    is too short to be worth a transfer."""
    from ..runtime.prefix_cache import PREFIX_MIN_TOKENS, bucket_down

    P = bucket_down(max(n_prompt_tokens - 1, 0), seq_len)
    return P if P >= PREFIX_MIN_TOKENS else 0


def run_prefill_arrays(state, ids: list, have_keys=(), trace=None):
    """The prefill-worker core, shared by BOTH transports: prefill
    ``ids[:P]`` under the serialized engine lock (riding the worker's OWN
    prefix cache, so a repeated shared prefix costs one splice instead of a
    re-prefill), skip the leading pages ``have_keys`` proves the requester
    already holds, and extract the rest as doubling segments.

    Returns ``(header, segments)``: ``segments`` is ``[(start, k, v), ...]``
    of device (or host) arrays covering tokens ``[S, P)`` — the device
    transport hands them over as-is (zero host serialization); the HTTP
    path (:func:`run_prefill`) flattens them into the binary payload.
    Raises ValueError for client errors (too short / too long); engine
    failures propagate for the handler's recover path."""
    import jax.numpy as jnp

    from ..runtime.prefix_cache import bucket_down, extract_prefix_from_row

    engine = state.engine
    n = len(ids)
    if n >= engine.cfg.seq_len:
        raise ValueError(
            f"prompt ({n} tokens) exceeds the context window ({engine.cfg.seq_len})"
        )
    P = prefill_boundary(n, engine.cfg.seq_len)
    if P <= 0:
        raise ValueError(
            f"prompt ({n} tokens) below the disaggregation floor"
        )
    expected = page_keys(ids[:P])
    # content-addressed skip: the longest leading run of the requester's
    # page names matching ours, floored to a prefix bucket (so the shipped
    # remainder splits into bucket-length doubling segments) and to the
    # worker's page granularity
    S = matching_pages(expected, have_keys) * KEY_PAGE_TOKENS
    S = bucket_down(S, engine.cfg.seq_len) if S else 0
    if engine.paged and S % engine.page_size != 0:
        S = 0
    with state.lock:
        t0 = time.perf_counter()
        engine.trace = trace
        try:
            engine.reset()
            # publish=True: the worker's own radix cache keeps the slice,
            # so the NEXT request sharing this prefix splices instead of
            # re-prefilling — the prefill tier has cache locality too
            engine.prefill(list(ids[:P]))
            segments = []
            if engine.paged:
                from ..runtime.paged_kv import gather_pages

                ps = engine.page_size
                pages = engine.page_pool.row_pages(0, P // ps)
                pc = engine.prefix_cache
                seg_sh = pc.seg_sharding if pc is not None else None
                for a, b_ in doubling_segments(S, P):
                    seg_pages = np.asarray(pages[a // ps : b_ // ps], np.int32)
                    B = b_ - a
                    with engine._guard(
                        f"page_extract[{B}]", ("page_extract", B, B)
                    ):
                        k, v = gather_pages(
                            engine.cache, seg_pages, out_sharding=seg_sh
                        )
                    segments.append((a, k, v))
            else:
                seg_sh = (
                    engine.prefix_cache.seg_sharding
                    if engine.prefix_cache is not None
                    else None
                )
                with engine._guard(
                    f"prefix_extract[{P}]", ("prefix_extract", P, P)
                ):
                    k, v = extract_prefix_from_row(
                        engine.cache, jnp.asarray(0, jnp.int32), length=P,
                        out_sharding=seg_sh,
                    )
                if S > 0:
                    # partial send: slice the skipped prefix off HOST-side
                    # (numpy views off one fetch — a cold path, and never
                    # an eager device op that could trip the sentinel)
                    k = np.asarray(k)[:, S:]
                    v = np.asarray(v)[:, S:]
                segments.append((S, k, v))
        finally:
            engine.trace = None
        wall_us = int((time.perf_counter() - t0) * 1e6)
    engine.stats.incr("disagg_prefills")
    engine.stats.incr("disagg_prefill_tokens", P - S)
    if S:
        engine.stats.incr("disagg_send_pages_skipped", S // KEY_PAGE_TOKENS)
    header = {
        "v": WIRE_VERSION,
        "tokens": [int(t) for t in ids[:P]],
        "p": P,
        "start": S,
        "page_tokens": KEY_PAGE_TOKENS,
        "page_keys": [format(h, "x") for h in expected],
        "prefill_us": wall_us,
    }
    return header, segments


def run_prefill(state, ids: list, have=(), trace=None) -> bytes:
    """The ``POST /v1/prefill`` body builder — the HTTP transport's worker
    half: run the shared core and flatten its segments into ONE binary
    payload (length-prefixed JSON header + raw k + raw v, covering tokens
    ``[start, P)``)."""
    header, segments = run_prefill_arrays(
        state, ids, have_keys=have, trace=trace
    )
    ks = [np.asarray(k) for _, k, _ in segments]
    vs = [np.asarray(v) for _, _, v in segments]
    k_np = np.concatenate(ks, axis=1) if len(ks) > 1 else ks[0]
    v_np = np.concatenate(vs, axis=1) if len(vs) > 1 else vs[0]
    # per-doubling-segment checksums over the CONCATENATED slice: layout-
    # independent (contiguous extract ships one segment, paged ships the
    # ladder — the receiver recomputes the same spans either way)
    S = int(header["start"])
    spans = doubling_segments(S, int(header["p"]))
    header = dict(
        header,
        k_shape=list(k_np.shape),
        v_shape=list(v_np.shape),
        dtype=str(k_np.dtype),
        k_sums=[
            format(segment_checksum(k_np[:, a - S : b - S].tobytes()), "x")
            for a, b in spans
        ],
        v_sums=[
            format(segment_checksum(v_np[:, a - S : b - S].tobytes()), "x")
            for a, b in spans
        ],
    )
    return kv_payload(header, k_np, v_np)


# -- the decode-worker side ---------------------------------------------------


class PendingExternalKv:
    """A fetched-but-not-yet-inserted KV slice. The insert MUST run on the
    engine's dispatch thread (a paged insert scatters into — donates — the
    live pool, which a handler thread must never race with the Batcher's
    dispatches), so the fetch defers it here: the Batcher applies it right
    before the request's admission; the serialized path applies it inline
    under the engine lock. ``base_entry`` stays PINNED until applied."""

    def __init__(self, client, tokens, k, v, start, base_entry, path):
        self.client = client
        self.tokens = tokens
        self.k = k  # array or per-segment list (kv_transport doubling order)
        self.v = v
        self.start = start
        self.base_entry = base_entry
        self.path = path
        self._applied = False

    def apply(self, state) -> bool:
        """Insert into the local prefix cache; idempotent. On refusal the
        request simply cold-prefills (counted; the transferred bytes were
        wasted — ledgered as transfer_retry so the loss is visible)."""
        if self._applied:
            return True
        self._applied = True
        engine = state.engine
        pc = engine.prefix_cache
        try:
            ok = pc.insert_external(
                engine, self.tokens, self.k, self.v, start=self.start,
                base_entry=self.base_entry,
            )
        finally:
            if self.base_entry is not None:
                pc.entry_release(self.base_entry)
            self.base_entry = None
        if not ok:
            engine.stats.incr("disagg_insert_failed")
            state.goodput.add_waste(
                "transfer_retry", len(self.tokens) - self.start
            )
        return ok

    def abandon(self):
        """Release the pinned base without inserting (failed request path
        between fetch and admission)."""
        if self.base_entry is not None:
            self.client.engine.prefix_cache.entry_release(self.base_entry)
            self.base_entry = None
        self._applied = True


class DisaggClient:
    """The decode worker's prefill-tier client: one bounded fetch per
    request over the per-peer transport (device when reachable, the HTTP
    codec otherwise — runtime/kv_transport.py), degraded to local prefill
    on ANY failure — a dead peer must cost this request one timeout, never
    an error. Peers rotate round-robin with in-request failover (the next
    peer is tried before degrading), and a FAILED peer enters a backoff
    window (``DLT_DISAGG_PEER_BACKOFF_S``, default 10 s) during which
    requests skip it — without this, a hung worker (accepts TCP, never
    answers) would add the full fetch timeout to EVERY request's TTFT
    until an operator intervened. With every peer backing off, requests
    prefill locally immediately (counted, no waste: no prefill-tier
    compute was spent). A successful fetch clears the peer's backoff.

    **Corrupt-peer quarantine** (the poison-request idiom rotated 90°):
    a transfer that arrives complete but WRONG — checksum mismatch,
    page_keys echo disagreement, garbage codec — is an integrity
    rejection, not a transport failure: the slice never touches the
    cache, the request degrades (or fails over) exactly as above, and
    the PEER takes a strike. ``DLT_KV_INTEGRITY_STRIKES`` strikes inside
    the ``DLT_KV_INTEGRITY_TTL_S`` redemption window drop the peer from
    rotation (composing with the fail-stop backoff — a peer can be both);
    the TTL expiring redeems it, so a transient corruptor (bad NIC since
    replaced, one stale process since restarted) is not banned forever.
    The ledger rides :meth:`snapshot` into ``/stats`` and — via the fleet
    scraper — ``/gateway/fleet``, so operators see WHICH replica emits
    garbage. A peer speaking an unknown wire version is skipped without a
    strike (``disagg_peer_version_mismatch``): mixed versions mean a
    rolling deploy, not corruption."""

    def __init__(self, state, peers, timeout_s: float | None = None,
                 backoff_s: float | None = None, transport: str | None = None,
                 integrity_strikes: int | None = None,
                 strike_ttl_s: float | None = None):
        self.state = state
        self.engine = state.engine
        self.peers = list(peers)
        if timeout_s is None:
            try:
                timeout_s = float(
                    os.environ.get("DLT_DISAGG_TIMEOUT_S", DEFAULT_TIMEOUT_S)
                )
            except ValueError:
                timeout_s = DEFAULT_TIMEOUT_S
        self.timeout_s = timeout_s
        if backoff_s is None:
            try:
                backoff_s = float(
                    os.environ.get("DLT_DISAGG_PEER_BACKOFF_S", 10.0)
                )
            except ValueError:
                backoff_s = 10.0
        self.backoff_s = backoff_s
        if integrity_strikes is None:
            try:
                integrity_strikes = int(
                    os.environ.get("DLT_KV_INTEGRITY_STRIKES", 3)
                )
            except ValueError:
                integrity_strikes = 3
        self.integrity_strikes = max(integrity_strikes, 1)
        if strike_ttl_s is None:
            try:
                strike_ttl_s = float(
                    os.environ.get("DLT_KV_INTEGRITY_TTL_S", 300.0)
                )
            except ValueError:
                strike_ttl_s = 300.0
        self.strike_ttl_s = strike_ttl_s
        self.transport = resolve_transport(transport)
        self.transports = build_transports(self.timeout_s)
        self._lock = threading.Lock()
        self._rr = 0
        self._backoff_until: dict = {}  # (host, port) -> monotonic deadline
        # the integrity strike ledger: (host, port) -> (count, ttl deadline).
        # Bounded by construction — keys come from self.peers only, and an
        # expired entry is pruned on its next read (TTL redemption).
        self._strikes: dict = {}

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            backing_off = [
                f"{h}:{p}" for (h, p), t in self._backoff_until.items()
                if t > now
            ]
            peer_strikes = {
                f"{h}:{p}": c
                for (h, p), (c, ttl) in self._strikes.items() if ttl > now
            }
            struck_out = [
                f"{h}:{p}"
                for (h, p), (c, ttl) in self._strikes.items()
                if ttl > now and c >= self.integrity_strikes
            ]
        return {
            "peers": [f"{h}:{p}" for h, p in self.peers],
            "timeout_s": self.timeout_s,
            "peer_backoff_s": self.backoff_s,
            "peers_backing_off": backing_off,
            "transport": self.transport,
            "peer_transports": {
                f"{h}:{p}": transport_for(
                    self.transport, (h, p), self.transports
                ).path
                for h, p in self.peers
            },
            "integrity": {
                "strikes_limit": self.integrity_strikes,
                "strike_ttl_s": self.strike_ttl_s,
                "peer_strikes": peer_strikes,
                "peers_struck_out": struck_out,
            },
        }

    def _peer_usable(self, peer) -> bool:
        now = time.monotonic()
        with self._lock:
            if self._backoff_until.get(peer, 0.0) > now:
                return False
            entry = self._strikes.get(peer)
            if entry is None:
                return True
            count, ttl = entry
            if ttl <= now:  # TTL redemption: the ban (and count) expires
                del self._strikes[peer]
                return True
            return count < self.integrity_strikes

    def _peer_failed(self, peer):
        with self._lock:
            self._backoff_until[peer] = time.monotonic() + self.backoff_s

    def _peer_strike(self, peer) -> int:
        """One integrity rejection = one strike; the TTL window restarts
        with each strike, so a steadily corrupting peer stays out."""
        now = time.monotonic()
        with self._lock:
            count, ttl = self._strikes.get(peer, (0, 0.0))
            if ttl <= now:
                count = 0
            count += 1
            self._strikes[peer] = (count, now + self.strike_ttl_s)
            return count

    def _peer_ok(self, peer):
        with self._lock:
            self._backoff_until.pop(peer, None)

    def _skip_base(self, ids, covered, entry):
        """(start, base_entry STILL PINNED or None, have_keys) — the
        content-addressed skip claim from a `match_pinned` result: the
        verified leading span floored to a prefix bucket of whole
        key-pages. Releases the pin itself (returning None) when the local
        cache holds nothing usable as a merge base."""
        from ..runtime.prefix_cache import bucket_down

        engine = self.engine
        pc = engine.prefix_cache
        if entry is None:
            return 0, None, ()
        S = bucket_down(min(covered, entry.length), engine.cfg.seq_len)
        if engine.paged and engine.page_size and S % engine.page_size != 0:
            S = 0
        if S < KEY_PAGE_TOKENS or tuple(entry.tokens[:S]) != tuple(
            int(t) for t in ids[:S]
        ):
            pc.entry_release(entry)
            return 0, None, ()
        return S, entry, page_keys(ids[:S])

    def fetch(self, ids: list, trace=None) -> dict:
        """Try to land ``ids``' leading-bucket KV ahead of admission.
        Returns the ledger walls ``{remote_prefill_us, kv_transfer_us,
        kv_transfer_path, transferred_tokens, pages_skipped}`` plus, under
        ``"pending_kv"``, the deferred insert the engine thread must apply
        (:class:`PendingExternalKv`; absent on local-hit/degraded paths).
        Zeros whenever the request proceeds on local prefill (short
        prompt, local cache already warm, or a degraded transfer). Never
        raises."""
        out = {
            "remote_prefill_us": 0, "kv_transfer_us": 0,
            "kv_transfer_path": "", "transferred_tokens": 0,
            "pages_skipped": 0, "pending_kv": None,
        }
        engine = self.engine
        pc = engine.prefix_cache
        if pc is None or not self.peers:
            return out
        P = prefill_boundary(len(ids), engine.cfg.seq_len)
        if P <= 0:
            return out
        # ONE trie walk, entry pinned under the match's own lock hold —
        # pool pressure must never evict-and-recycle the merge base's
        # pages between the lookup and the insert that names them
        covered, matched = pc.match_pinned(ids[:P])
        if matched is not None and covered >= P:
            # the local cache already holds the span (an earlier transfer,
            # or plain cross-request reuse): nothing to ship
            pc.entry_release(matched)
            engine.stats.incr("disagg_local_hits")
            return out
        usable = [p for p in self.peers if self._peer_usable(p)]
        if not usable:
            # every peer is in its failure-backoff window: prefill locally
            # NOW instead of burning a timeout per request on known-bad
            # peers. Not waste — no prefill-tier compute was spent.
            if matched is not None:
                pc.entry_release(matched)
            engine.stats.incr("disagg_peer_backoff_skips")
            return out
        S, base_entry, have = self._skip_base(ids, covered, matched)
        t0 = time.perf_counter()
        result = None
        peer_key = None
        err = None
        rejected_peer = None  # last integrity-rejected peer (one trace event)
        rejected_err = ""
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % len(usable)
        for i in range(len(usable)):
            peer = usable[(start + i) % len(usable)]
            host, port = peer
            tr_impl = transport_for(self.transport, peer, self.transports)
            try:
                # ship ids[:P+1]: the worker derives the SAME boundary from
                # the same formula (bucket_down over len-1), so its slice
                # covers exactly ids[:P] — truncating at P would make the
                # worker floor one bucket lower
                got = tr_impl.fetch(
                    peer, ids[: P + 1], have_keys=have,
                    trace_id=None if trace is None else trace.id,
                )
                # THE integrity gate: checksums + page_keys echo (http) /
                # metadata (device) verified BEFORE the slice can reach
                # insert_external — a passing result is the only kind the
                # rest of this function ever sees
                verify_transfer(got, ids, P)
                result = got
                peer_key = f"{host}:{port}"
                self._peer_ok(peer)
                engine.stats.incr("kv_integrity_verified")
                break
            except KvVersionError as e:
                # the peer is healthy, just mid-rolling-deploy on another
                # wire version: skip it for this request — no strike, no
                # backoff (it would quarantine an innocent replica)
                err = e
                engine.stats.incr("disagg_peer_version_mismatch")
            except KvCodecError as e:
                # complete response, wrong content: corruption. Reject
                # before the cache is touched and strike the PEER — enough
                # strikes inside the TTL drop it from rotation entirely.
                err = e
                engine.stats.incr("kv_integrity_rejected")
                rejected_peer = f"{host}:{port}"
                rejected_err = f"{type(e).__name__}: {e}"
                self._peer_strike(peer)
            except Exception as e:
                # OSError: refused/reset/timeout; HTTPException covers
                # mid-body deaths; the device path raises the same
                # families. A fail-stop transfer failure is a peer failure,
                # never a request failure — the degradation contract
                # (counted below, the error rides the kv_transfer event).
                err = e
                engine.stats.incr("disagg_peer_errors")
                self._peer_failed(peer)
        pending = None
        if result is not None:
            try:
                header = result.header
                tokens = [int(t) for t in header["tokens"]]
                if tokens != [int(t) for t in ids[:P]]:
                    raise ValueError("peer returned KV for different tokens")
                r_start = int(header.get("start", 0))
                if r_start != S:
                    # the worker floored differently (defensive path); a
                    # full send is still insertable, anything else is not
                    if r_start == 0:
                        if base_entry is not None:
                            pc.entry_release(base_entry)
                        base_entry = None
                        S = 0
                    else:
                        raise ValueError(
                            f"peer shipped start={r_start}, asked {S}"
                        )
                pending = PendingExternalKv(
                    self, tokens, result.k, result.v, S, base_entry, result.path
                )
                base_entry = None  # ownership moved to the pending insert
                out["remote_prefill_us"] = int(header.get("prefill_us", 0))
                out["transferred_tokens"] = P - S
                out["pages_skipped"] = S // KEY_PAGE_TOKENS
            except (ValueError, KeyError, TypeError) as e:
                err = e
                pending = None
        if base_entry is not None:
            pc.entry_release(base_entry)
        from ..runtime.tracing import to_us

        wall_us = int((time.perf_counter() - t0) * 1e6)
        if rejected_peer is not None and trace is not None:
            # ONE event per fetch, outside the peer loop (trace-hot-emit
            # lint), landed even unsampled AND even when failover to a
            # clean peer saved the request: a corrupting replica must be
            # reconstructable from any trace that touched it
            trace.event(
                "kv_integrity", to_us(t0), wall_us,
                ("peer", "outcome", "error"),
                (rejected_peer, "rejected", rejected_err),
                always=True,
            )
        if pending is not None:
            # the transfer share of the wall: the fetch blocks on the
            # worker's prefill too, which the worker reports separately.
            # Per-path accounting: the labeled dlt_kv_transfer_us series
            # and dlt_kv_transfer_bytes_total{path=...} counters are what
            # the device-vs-http bench bar reads.
            path = pending.path
            transfer_us = max(wall_us - out["remote_prefill_us"], 0)
            out["kv_transfer_us"] = transfer_us
            out["kv_transfer_path"] = path
            out["pending_kv"] = pending
            engine.stats.incr("disagg_kv_fetched")
            engine.stats.incr("disagg_kv_tokens", P - S)
            if out["pages_skipped"]:
                engine.stats.incr("disagg_pages_skipped", out["pages_skipped"])
            engine.stats.record(f"kv_transfer_us[{path}]", transfer_us)
            engine.stats.incr(f"kv_transfer_bytes_{path}", result.nbytes)
            if trace is not None:
                trace.event(
                    "kv_transfer", to_us(t0), wall_us,
                    ("peer", "tokens", "failed", "path", "pages_skipped"),
                    (peer_key, P - S, 0, path, out["pages_skipped"]),
                )
        else:
            # DEGRADE to local prefill: the request must complete (token-
            # identical — it simply takes the unified path). Counted,
            # ledgered as waste (the P tokens the prefill tier computed —
            # or would have — now re-prefill locally), and traced even
            # unsampled so a chaos kill is reconstructable. The waste
            # reason splits the why: `integrity` when the last failure was
            # a complete-but-corrupt response, `transfer_retry` for the
            # fail-stop families (dead peer, version skew, mid-body death).
            engine.stats.incr("disagg_degraded")
            engine.stats.incr("disagg_degraded_tokens", P)
            reason = (
                "integrity"
                if isinstance(err, KvCodecError)
                and not isinstance(err, KvVersionError)
                else "transfer_retry"
            )
            self.state.goodput.add_waste(reason, P)
            if trace is not None:
                trace.event(
                    "kv_transfer", to_us(t0), wall_us,
                    ("peer", "tokens", "failed", "error"),
                    (
                        peer_key or "none", P, 1,
                        "" if err is None else f"{type(err).__name__}: {err}",
                    ),
                    always=True,
                )
        return out
