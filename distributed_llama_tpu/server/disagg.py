"""Prefill/decode disaggregation: dedicated prefill workers ship KV.

TTFT-heavy and decode-heavy traffic contend for the same chips on a unified
replica: one long prompt's prefill chunks interleave with — and bound the
latency of — every co-batched decode stream. DistServe's answer (and ours)
is to split the roles: **prefill workers** run prompts and ship the finished
KV; **decode workers** splice it and stream tokens. The split rides this
repo's existing machinery end to end:

* the prefill worker runs an ordinary ``engine.prefill`` over the prompt's
  leading ``P`` tokens (``P`` = the prefix cache's bucket_down boundary, so
  the shipped slice lands exactly on the warm copy-program ladder) and
  extracts ``[L, P, h, d]`` k/v with the SAME ``extract_prefix_from_row``
  program a local publish uses (``POST /v1/prefill`` -> one binary payload:
  length-prefixed JSON header + raw k + raw v);
* the decode worker inserts the shipped slice into its radix prefix cache
  (:meth:`~..runtime.prefix_cache.PrefixCache.insert_external`), and the
  request then takes the UNMODIFIED admission path — match, pin, splice,
  resume — which is what makes disaggregated output bit-identical to
  unified serving (the prefix cache's write-before-read invariant already
  proves splice-then-resume ≡ cold prefill);
* **degradation, not failure**: a prefill worker dying mid-transfer (the
  chaos suite kills one mid-KV-body) leaves the decode worker exactly one
  request-local consequence — no cache entry — so the request cold-prefills
  locally and completes token-identical. The event is counted
  (``disagg_degraded``), ledgered (the re-prefilled tokens land in
  ``dlt_wasted_tokens_total{reason=transfer_retry}`` — the prefill worker's
  compute for them is lost fleet-wide), and traced (a ``kv_transfer`` event
  with ``failed=1`` lands even on unsampled traces).

Roles are picked with ``--role {prefill,decode,unified}`` (``DLT_ROLE``) on
the API server; decode workers name their peers with ``--prefill-peer
host:port`` (repeatable; ``DLT_PREFILL_PEER`` comma-separated). Both
disaggregated roles force the contiguous KV layout: the wire format is host
arrays, and a paged entry's storage is physical page ids that mean nothing
outside their own pool.
"""

from __future__ import annotations

import http.client
import json
import os
import struct
import threading
import time

import numpy as np

ROLES = ("unified", "prefill", "decode")

#: decode->prefill-worker round-trip budget (connect + prefill + transfer);
#: generous because the worker's wall includes real prefill compute
DEFAULT_TIMEOUT_S = 30.0


def resolve_role(explicit=None) -> str:
    """``--role`` flag > ``DLT_ROLE`` env > unified. Unknown values raise:
    a typo'd role silently serving unified would defeat the topology."""
    role = explicit or os.environ.get("DLT_ROLE") or "unified"
    if role not in ROLES:
        raise ValueError(f"unknown serving role {role!r} (one of {ROLES})")
    return role


def resolve_peers(explicit=None) -> list:
    """``--prefill-peer`` (repeatable) > ``DLT_PREFILL_PEER`` (comma-
    separated) > none. Returns ``[(host, port), ...]``."""
    raw = list(explicit) if explicit else [
        s for s in os.environ.get("DLT_PREFILL_PEER", "").split(",") if s.strip()
    ]
    peers = []
    for s in raw:
        host, _, port = s.strip().rpartition(":")
        peers.append((host or "127.0.0.1", int(port)))
    return peers


def _np_dtype(name: str):
    """Dtype-by-name incl. the ml_dtypes extended floats (``np.dtype`` alone
    does not know ``bfloat16``)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


# -- the wire format ----------------------------------------------------------
#
# 4-byte big-endian header length | JSON header | raw k bytes | raw v bytes
# Header: tokens (the P token ids the slice covers), k_shape/v_shape, dtype,
# prefill_us (the worker's wall — the decode side's ledger field). Raw bytes
# rather than base64-in-JSON: a 512-token 8B-class slice is tens of MB and
# the transfer wall is the metric under test.


def kv_payload(header: dict, k_np: np.ndarray, v_np: np.ndarray) -> bytes:
    hjson = json.dumps(header).encode()
    return struct.pack(">I", len(hjson)) + hjson + k_np.tobytes() + v_np.tobytes()


def parse_kv_payload(body: bytes):
    """``(header, k_np, v_np)`` from one payload; raises ValueError on any
    truncation or shape/dtype mismatch (the caller's degradation path)."""
    if len(body) < 4:
        raise ValueError("kv payload truncated before header length")
    (hlen,) = struct.unpack(">I", body[:4])
    if len(body) < 4 + hlen:
        raise ValueError("kv payload truncated inside header")
    header = json.loads(body[4 : 4 + hlen])
    dt = _np_dtype(header["dtype"])
    k_shape = tuple(header["k_shape"])
    v_shape = tuple(header["v_shape"])
    k_bytes = int(np.prod(k_shape)) * dt.itemsize
    v_bytes = int(np.prod(v_shape)) * dt.itemsize
    blob = body[4 + hlen :]
    if len(blob) != k_bytes + v_bytes:
        raise ValueError(
            f"kv payload truncated: body {len(blob)} B, "
            f"header names {k_bytes + v_bytes} B"
        )
    k = np.frombuffer(blob[:k_bytes], dtype=dt).reshape(k_shape)
    v = np.frombuffer(blob[k_bytes:], dtype=dt).reshape(v_shape)
    return header, k, v


# -- the prefill-worker side --------------------------------------------------


def prefill_boundary(n_prompt_tokens: int, seq_len: int) -> int:
    """The bucket boundary a disaggregated transfer covers: the largest
    prefix bucket <= the prompt's prefillable span (the last prompt token is
    fed at decode time, exactly like the local publish cap). 0 = the prompt
    is too short to be worth a transfer."""
    from ..runtime.prefix_cache import PREFIX_MIN_TOKENS, bucket_down

    P = bucket_down(max(n_prompt_tokens - 1, 0), seq_len)
    return P if P >= PREFIX_MIN_TOKENS else 0


def run_prefill(state, ids: list, trace=None) -> bytes:
    """The ``POST /v1/prefill`` body builder, run on the prefill worker
    under its serialized engine lock: prefill ``ids[:P]`` (riding the
    worker's OWN prefix cache, so a repeated shared prefix costs one splice
    instead of a re-prefill), extract the slice through the warmed
    ``prefix_extract`` program, and frame it for the wire. Raises ValueError
    for client errors (too short / too long); engine failures propagate for
    the handler's recover path."""
    import jax.numpy as jnp

    from ..runtime.prefix_cache import extract_prefix_from_row

    engine = state.engine
    if engine.paged:
        raise ValueError("prefill role requires the contiguous KV layout")
    n = len(ids)
    if n >= engine.cfg.seq_len:
        raise ValueError(
            f"prompt ({n} tokens) exceeds the context window ({engine.cfg.seq_len})"
        )
    P = prefill_boundary(n, engine.cfg.seq_len)
    if P <= 0:
        raise ValueError(
            f"prompt ({n} tokens) below the disaggregation floor"
        )
    with state.lock:
        t0 = time.perf_counter()
        engine.trace = trace
        try:
            engine.reset()
            # publish=True: the worker's own radix cache keeps the slice,
            # so the NEXT request sharing this prefix splices instead of
            # re-prefilling — the prefill tier has cache locality too
            engine.prefill(list(ids[:P]))
            seg_sh = (
                engine.prefix_cache.seg_sharding
                if engine.prefix_cache is not None
                else None
            )
            with engine._guard(f"prefix_extract[{P}]", ("prefix_extract", P, P)):
                k, v = extract_prefix_from_row(
                    engine.cache, jnp.asarray(0, jnp.int32), length=P,
                    out_sharding=seg_sh,
                )
            k_np = np.asarray(k)
            v_np = np.asarray(v)
        finally:
            engine.trace = None
        wall_us = int((time.perf_counter() - t0) * 1e6)
    engine.stats.incr("disagg_prefills")
    engine.stats.incr("disagg_prefill_tokens", P)
    header = {
        "tokens": [int(t) for t in ids[:P]],
        "p": P,
        "k_shape": list(k_np.shape),
        "v_shape": list(v_np.shape),
        "dtype": str(k_np.dtype),
        "prefill_us": wall_us,
    }
    return kv_payload(header, k_np, v_np)


# -- the decode-worker side ---------------------------------------------------


class DisaggClient:
    """The decode worker's prefill-tier client: one bounded fetch per
    request, inserted into the local radix cache on success, degraded to
    local prefill on ANY failure — a dead peer must cost this request one
    timeout, never an error. Peers rotate round-robin with in-request
    failover (the next peer is tried before degrading), and a FAILED peer
    enters a backoff window (``DLT_DISAGG_PEER_BACKOFF_S``, default 10 s)
    during which requests skip it — without this, a hung worker (accepts
    TCP, never answers) would add the full fetch timeout to EVERY
    request's TTFT until an operator intervened. With every peer backing
    off, requests prefill locally immediately (counted, no waste: no
    prefill-tier compute was spent). A successful fetch clears the peer's
    backoff."""

    def __init__(self, state, peers, timeout_s: float | None = None,
                 backoff_s: float | None = None):
        self.state = state
        self.engine = state.engine
        self.peers = list(peers)
        if timeout_s is None:
            try:
                timeout_s = float(
                    os.environ.get("DLT_DISAGG_TIMEOUT_S", DEFAULT_TIMEOUT_S)
                )
            except ValueError:
                timeout_s = DEFAULT_TIMEOUT_S
        self.timeout_s = timeout_s
        if backoff_s is None:
            try:
                backoff_s = float(
                    os.environ.get("DLT_DISAGG_PEER_BACKOFF_S", 10.0)
                )
            except ValueError:
                backoff_s = 10.0
        self.backoff_s = backoff_s
        self._lock = threading.Lock()
        self._rr = 0
        self._backoff_until: dict = {}  # (host, port) -> monotonic deadline

    def snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            backing_off = [
                f"{h}:{p}" for (h, p), t in self._backoff_until.items()
                if t > now
            ]
        return {
            "peers": [f"{h}:{p}" for h, p in self.peers],
            "timeout_s": self.timeout_s,
            "peer_backoff_s": self.backoff_s,
            "peers_backing_off": backing_off,
        }

    def _peer_usable(self, peer) -> bool:
        with self._lock:
            return self._backoff_until.get(peer, 0.0) <= time.monotonic()

    def _peer_failed(self, peer):
        with self._lock:
            self._backoff_until[peer] = time.monotonic() + self.backoff_s

    def _peer_ok(self, peer):
        with self._lock:
            self._backoff_until.pop(peer, None)

    def _fetch_one(self, host: str, port: int, ids: list, trace_id=None):
        from ..runtime.tracing import TRACE_HEADER

        conn = http.client.HTTPConnection(host, port, timeout=self.timeout_s)
        try:
            headers = {"Content-Type": "application/json", "Connection": "close"}
            if trace_id:
                headers[TRACE_HEADER] = trace_id
            conn.request(
                "POST", "/v1/prefill", body=json.dumps({"ids": ids}),
                headers=headers,
            )
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise OSError(f"/v1/prefill returned {resp.status}")
            return body
        finally:
            conn.close()

    def fetch(self, ids: list, trace=None) -> dict:
        """Try to land ``ids``' leading-bucket KV in the local prefix cache
        ahead of admission. Returns the ledger walls
        ``{remote_prefill_us, kv_transfer_us, transferred_tokens}`` —
        zeros whenever the request proceeds on local prefill (short prompt,
        local cache already warm, or a degraded transfer). Never raises."""
        out = {"remote_prefill_us": 0, "kv_transfer_us": 0, "transferred_tokens": 0}
        engine = self.engine
        pc = engine.prefix_cache
        if pc is None or engine.paged or not self.peers:
            return out
        P = prefill_boundary(len(ids), engine.cfg.seq_len)
        if P <= 0:
            return out
        covered, _entry = pc.match(ids[:P])
        if covered >= P:
            # the local cache already holds the span (an earlier transfer,
            # or plain cross-request reuse): nothing to ship
            engine.stats.incr("disagg_local_hits")
            return out
        usable = [p for p in self.peers if self._peer_usable(p)]
        if not usable:
            # every peer is in its failure-backoff window: prefill locally
            # NOW instead of burning a timeout per request on known-bad
            # peers. Not waste — no prefill-tier compute was spent.
            engine.stats.incr("disagg_peer_backoff_skips")
            return out
        t0 = time.perf_counter()
        body = None
        peer_key = None
        err = None
        with self._lock:
            start = self._rr
            self._rr = (self._rr + 1) % len(usable)
        for i in range(len(usable)):
            peer = usable[(start + i) % len(usable)]
            host, port = peer
            try:
                # ship ids[:P+1]: the worker derives the SAME boundary from
                # the same formula (bucket_down over len-1), so its slice
                # covers exactly ids[:P] — truncating at P would make the
                # worker floor one bucket lower
                body = self._fetch_one(
                    host, port, ids[: P + 1],
                    trace_id=None if trace is None else trace.id,
                )
                peer_key = f"{host}:{port}"
                self._peer_ok(peer)
                break
            except (OSError, ValueError, http.client.HTTPException) as e:
                # OSError: refused/reset/timeout; HTTPException: a mid-body
                # death that surfaces as IncompleteRead/BadStatusLine — all
                # the chaos suite's kill shapes land here
                err = e
                engine.stats.incr("disagg_peer_errors")
                self._peer_failed(peer)
        inserted = False
        if body is not None:
            try:
                header, k_np, v_np = parse_kv_payload(body)
                tokens = header["tokens"]
                if tokens != [int(t) for t in ids[:P]]:
                    raise ValueError("peer returned KV for different tokens")
                inserted = pc.insert_external(engine, tokens, k_np, v_np)
                if not inserted:
                    raise ValueError("local cache refused the external slice")
                out["remote_prefill_us"] = int(header.get("prefill_us", 0))
                out["transferred_tokens"] = P
            except (ValueError, KeyError, TypeError) as e:
                err = e
                inserted = False
        from ..runtime.tracing import to_us

        wall_us = int((time.perf_counter() - t0) * 1e6)
        if inserted:
            # the transfer share of the wall: the fetch blocks on the
            # worker's prefill too, which the worker reports separately
            out["kv_transfer_us"] = max(wall_us - out["remote_prefill_us"], 0)
            engine.stats.incr("disagg_kv_fetched")
            engine.stats.incr("disagg_kv_tokens", P)
            if trace is not None:
                trace.event(
                    "kv_transfer", to_us(t0), wall_us,
                    ("peer", "tokens", "failed"), (peer_key, P, 0),
                )
        else:
            # DEGRADE to local prefill: the request must complete (token-
            # identical — it simply takes the unified path). Counted,
            # ledgered as transfer_retry waste (the P tokens the prefill
            # tier computed — or would have — now re-prefill locally), and
            # traced even unsampled so a chaos kill is reconstructable.
            engine.stats.incr("disagg_degraded")
            engine.stats.incr("disagg_degraded_tokens", P)
            self.state.goodput.add_waste("transfer_retry", P)
            if trace is not None:
                trace.event(
                    "kv_transfer", to_us(t0), wall_us,
                    ("peer", "tokens", "failed", "error"),
                    (
                        peer_key or "none", P, 1,
                        "" if err is None else f"{type(err).__name__}: {err}",
                    ),
                    always=True,
                )
        return out
