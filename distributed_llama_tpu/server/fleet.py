"""Fleet signal plane: gateway-side metrics federation over replicas.

PR 6 made the request path observable and PR 7 made the device observable,
but the gateway balanced on inflight counts alone — it had no idea which
replica holds a hot prefix cache, which one's KV page pool is about to
exhaust, or which one is missing its TTFT SLO. This module closes that
gap: a background :class:`FleetScraper` polls every backend's ``/metrics``
(+ ``/stats``) on an interval and maintains a per-replica signal table —
exactly the inputs a prefix-cache-aware router (ROADMAP item 3) scores:

* **prefix_hit_tokens rate** (tokens/s reused from the radix cache —
  derived from consecutive scrapes of the cumulative counter);
* **KV-pool headroom** (``kv_pool_pages_free`` / ``_used`` gauges from the
  paged allocator; absent on contiguous replicas);
* **batcher occupancy** (active/prefilling slots, queue depth, backlog
  cap — how loaded the replica's continuous-batching loop really is,
  which raw inflight connection counts under-report during prefill);
* **SLO attainment** (``slo_ttft_attainment`` / ``slo_tpot_attainment``
  gauges the PR 7 layer derives from the cumulative latency histograms);
* **goodput** (``goodput_tokens_per_s`` — delivered-token rate net of
  waste, the PR 9 ledger's headline gauge);
* **staleness** (seconds since the last successful scrape — a replica
  that stopped answering keeps its last-known signals, flagged stale, so
  the router can discount rather than crash on it).

The scraper is **failure-isolated by construction**: every poll runs in
its own try/except, a dead backend just ages into staleness (the chaos
suite kills one mid-scrape and asserts no exception escapes), and no
client request ever waits on a scrape. Serving:

* ``GET /gateway/fleet`` — the signal table as JSON (breaker state joined
  from the balancer, so the router view and the failure view can't
  disagree);
* ``GET /metrics`` on the gateway — the gateway's own series plus a
  **federated rollup**: every replica's scraped samples re-emitted with a
  ``replica="host:port"`` label, so one Prometheus scrape of the gateway
  sees the whole fleet (the reference's per-node network perf reports
  print at shutdown, per node; this is the live, joined equivalent).

Deliberately stdlib-only (no jax, no numpy): the gateway imports this and
must stay runnable on a box with no accelerator stack.
"""

from __future__ import annotations

import http.client
import json
import os
import threading
import time

def now_s() -> float:
    """Monotonic seconds — the staleness clock (module-level so tests can
    drive time explicitly by patching it)."""
    return time.monotonic()


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


#: scrape cadence (seconds); <= 0 disables the scraper thread entirely
DEFAULT_SCRAPE_S = 2.0
#: per-request socket timeout for one scrape round trip
DEFAULT_TIMEOUT_S = 2.0


def http_get_text(host: str, port: int, path: str, timeout_s: float) -> tuple:
    """One bounded GET round trip: ``(status, body_text)``. Raises OSError
    family on transport failure — callers isolate."""
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request("GET", path, headers={"Connection": "close"})
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", errors="replace")
    finally:
        conn.close()


def http_post_json(
    host: str, port: int, path: str, payload: dict, timeout_s: float
) -> tuple:
    """One bounded JSON POST round trip: ``(status, body_text)``. The
    gateway-to-gateway peer sync and the replica drain-hint notification
    both ride this — same transport discipline as the scraper: raises
    OSError family on failure, callers isolate."""
    body = json.dumps(payload).encode()
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        conn.request(
            "POST", path, body=body,
            headers={
                "Content-Type": "application/json",
                "Connection": "close",
            },
        )
        resp = conn.getresponse()
        return resp.status, resp.read().decode("utf-8", errors="replace")
    finally:
        conn.close()


# -- Prometheus text parsing -------------------------------------------------


def parse_prom_text(body: str) -> tuple:
    """Parse Prometheus text exposition into ``(samples, types)`` where
    samples is ``[(name, labels_dict, value), ...]`` (file order kept) and
    types maps metric family name -> declared type. Tolerant: unparseable
    lines are skipped, never raised — this runs against replicas mid-crash."""
    samples: list = []
    types: dict = {}
    for line in body.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            continue
        # NAME{label="v",...} VALUE   |   NAME VALUE
        try:
            if "{" in line:
                name, rest = line.split("{", 1)
                labstr, valstr = rest.rsplit("}", 1)
                labels = {}
                for item in _split_labels(labstr):
                    k, v = item.split("=", 1)
                    labels[k.strip()] = v.strip().strip('"')
            else:
                name, valstr = line.rsplit(None, 1)
                labels = {}
            value = float(valstr)
        except (ValueError, IndexError):
            continue
        samples.append((name.strip(), labels, value))
    return samples, types


def _split_labels(labstr: str) -> list:
    """Split ``a="x",b="y,z"`` on commas outside quotes."""
    out, cur, in_q, prev = [], [], False, ""
    for ch in labstr:
        if ch == '"' and prev != "\\":
            in_q = not in_q
        if ch == "," and not in_q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
        prev = ch
    if cur:
        out.append("".join(cur))
    return [s for s in (x.strip() for x in out) if s]


# -- the per-replica signal table --------------------------------------------

#: unlabeled gauges lifted verbatim into the signal table when present
_GAUGE_SIGNALS = {
    "dlt_kv_pool_pages_free": "kv_pool_pages_free",
    "dlt_kv_pool_pages_used": "kv_pool_pages_used",
    "dlt_batcher_slots_active": "batcher_slots_active",
    "dlt_batcher_slots_prefilling": "batcher_slots_prefilling",
    "dlt_batcher_batch_slots": "batcher_batch_slots",
    "dlt_batcher_queue_depth": "batcher_queue_depth",
    "dlt_batcher_max_backlog": "batcher_max_backlog",
    "dlt_slo_ttft_attainment": "slo_ttft_attainment",
    "dlt_slo_tpot_attainment": "slo_tpot_attainment",
    "dlt_goodput_tokens_per_s": "goodput_tokens_per_s",
    "dlt_prefix_cache_bytes": "prefix_cache_bytes",
    "dlt_prefix_cache_entries": "prefix_cache_entries",
    # tiered KV store (runtime/kv_tiering.py): per-tier occupancy, so
    # router scoring and autoscaler drain-handoff are tier-aware
    "dlt_kv_tier_host_bytes": "kv_tier_host_bytes",
    "dlt_kv_tier_host_budget_bytes": "kv_tier_host_budget_bytes",
    "dlt_kv_tier_host_entries": "kv_tier_host_entries",
    "dlt_kv_tier_disk_bytes": "kv_tier_disk_bytes",
    "dlt_kv_tier_disk_entries": "kv_tier_disk_entries",
}

#: cumulative counters turned into rates across consecutive scrapes
_RATE_SIGNALS = {
    "dlt_prefix_hit_tokens_total": "prefix_hit_tokens_per_s",
    "dlt_requests_completed_total": "requests_per_s",
    "dlt_shed_503_total": "shed_per_s",
    "dlt_kv_tier_promoted_tokens_total": "kv_tier_promoted_tokens_per_s",
}


class ReplicaState:
    """Last-known signals + scrape bookkeeping for one backend. Mutated
    only by the scraper thread; snapshot readers copy under the fleet lock."""

    __slots__ = (
        "key", "signals", "samples", "types", "stats_sections",
        "last_ok_s", "last_attempt_s", "scrapes_ok", "scrape_failures",
        "consecutive_failures", "_prev_counters", "_prev_t",
    )

    def __init__(self, key: str):
        self.key = key
        self.signals: dict = {}
        self.samples: list = []  # parsed /metrics samples, for federation
        self.types: dict = {}
        self.stats_sections: dict = {}  # selected /stats fields
        self.last_ok_s: float | None = None
        self.last_attempt_s: float | None = None
        self.scrapes_ok = 0
        self.scrape_failures = 0
        self.consecutive_failures = 0
        self._prev_counters: dict = {}
        self._prev_t: float | None = None


class FleetScraper:
    """Background per-replica ``/metrics`` (+ ``/stats``) poller over a
    gateway :class:`~.gateway.Balancer`. Construct and call
    :meth:`scrape_once` directly in tests; :meth:`start` runs the loop.

    The contract every caller relies on: **no exception ever escapes a
    scrape** — a replica that refuses, stalls, or returns garbage is
    counted, aged toward staleness, and retried next interval."""

    def __init__(
        self,
        balancer,
        interval_s: float | None = None,
        timeout_s: float | None = None,
        stale_after_s: float | None = None,
    ):
        self.balancer = balancer
        self.interval_s = (
            _env_float("DLT_FLEET_SCRAPE_S", DEFAULT_SCRAPE_S)
            if interval_s is None
            else interval_s
        )
        self.timeout_s = (
            _env_float("DLT_FLEET_TIMEOUT_S", DEFAULT_TIMEOUT_S)
            if timeout_s is None
            else timeout_s
        )
        # a replica is STALE once its last good scrape is older than this
        # (default: 3 intervals — one flaky scrape must not flap the flag)
        self.stale_after_s = (
            _env_float("DLT_FLEET_STALE_S", 3.0 * max(self.interval_s, 0.1))
            if stale_after_s is None
            else stale_after_s
        )
        self._lock = threading.Lock()
        self._replicas: dict = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.scrape_rounds = 0

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetScraper":
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="gateway-fleet-scraper"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self.scrape_once()

    # -- scraping -----------------------------------------------------------

    def _replica(self, key: str) -> ReplicaState:
        st = self._replicas.get(key)
        if st is None:
            st = self._replicas[key] = ReplicaState(key)
        return st

    def scrape_once(self):
        """One scrape round over every configured backend. Never raises."""
        for b in list(self.balancer.config.backends):
            try:
                self._scrape_backend(b)
            except Exception:
                # belt over the per-fetch suspenders: a scrape must never
                # kill the thread (a live request does not depend on it,
                # but a dead scraper silently freezes the routing signals)
                with self._lock:
                    st = self._replica(b.key)
                    st.scrape_failures += 1
                    st.consecutive_failures += 1
        self.scrape_rounds += 1

    def _scrape_backend(self, b):
        now = now_s()
        key = b.key
        try:
            status, body = http_get_text(b.host, b.port, "/metrics", self.timeout_s)
            if status != 200:
                raise OSError(f"/metrics returned {status}")
            samples, types = parse_prom_text(body)
            stats_sections = self._fetch_stats(b)
        except Exception:
            with self._lock:
                st = self._replica(key)
                st.last_attempt_s = now
                st.scrape_failures += 1
                st.consecutive_failures += 1
            return
        signals: dict = {}
        counters: dict = {}
        goodput_by_class: dict = {}
        attainment_by_class: dict = {}
        for name, labels, value in samples:
            if labels:
                # per-SLO-class breakdowns (server/scheduler.py): the
                # slo_class-labeled rows of the goodput and attainment
                # gauge families ride the signal table so /gateway/fleet
                # and the autoscaler see per-class delivery/SLO health
                # without re-parsing (replicas that don't emit per-class
                # attainment — only the class-blended aggregate exists
                # today on real engines — simply have no rows here)
                if "slo_class" in labels:
                    if name == "dlt_goodput_tokens_per_s":
                        goodput_by_class[labels["slo_class"]] = value
                    elif name == "dlt_slo_ttft_attainment":
                        attainment_by_class[labels["slo_class"]] = value
                continue
            if name in _GAUGE_SIGNALS:
                signals[_GAUGE_SIGNALS[name]] = value
            elif name in _RATE_SIGNALS:
                counters[name] = value
        if goodput_by_class:
            signals["goodput_by_class"] = goodput_by_class
        if attainment_by_class:
            signals["slo_ttft_attainment_by_class"] = attainment_by_class
        with self._lock:
            st = self._replica(key)
            st.last_attempt_s = now
            # counter -> rate across consecutive good scrapes. A counter
            # that went BACKWARD (replica restarted) resets the baseline
            # instead of reporting a huge negative rate.
            if st._prev_t is not None and now > st._prev_t:
                dt = now - st._prev_t
                for cname, cur in counters.items():
                    prev = st._prev_counters.get(cname)
                    if prev is not None and cur >= prev:
                        signals[_RATE_SIGNALS[cname]] = round((cur - prev) / dt, 3)
            st._prev_counters = counters
            st._prev_t = now
            st.signals = signals
            st.samples = samples
            st.types = types
            st.stats_sections = stats_sections
            st.last_ok_s = now
            st.scrapes_ok += 1
            st.consecutive_failures = 0

    def _fetch_stats(self, b) -> dict:
        """Selected ``/stats`` sections (config-ish context the flat
        metrics don't carry). Best-effort: a replica without /stats — or a
        mid-crash one — just yields an empty dict."""
        try:
            status, body = http_get_text(b.host, b.port, "/stats", self.timeout_s)
            if status != 200:
                return {}
            payload = json.loads(body)
        except Exception:
            return {}
        out = {}
        for k in ("batcher", "kv_pool", "speculative", "batch", "seq_len",
                  "role", "disagg", "scheduler", "kv_tiering"):
            if isinstance(payload, dict) and payload.get(k) is not None:
                out[k] = payload[k]
        return out

    # -- views ---------------------------------------------------------------

    def router_signals(self) -> dict:
        """The router's per-request view (server/router.py): one lock hold,
        no balancer join — ``{backend_key: {stale, age_s, signals}}``. A
        never-scraped replica simply has no row (the router treats absence
        as stale)."""
        now = now_s()
        with self._lock:
            out = {}
            for k, st in self._replicas.items():
                age = None if st.last_ok_s is None else now - st.last_ok_s
                out[k] = {
                    "stale": age is None or age > self.stale_after_s,
                    "age_s": age,
                    "signals": dict(st.signals),
                }
        return out

    def snapshot(self) -> dict:
        """The ``/gateway/fleet`` payload: one row per backend, signal
        table joined with the balancer's breaker/inflight/draining state."""
        now = now_s()
        balancer_state = {
            s["backend"]: s for s in self.balancer.stats()["backends"]
        }
        rows = []
        with self._lock:
            replicas = {k: v for k, v in self._replicas.items()}
        for b in list(self.balancer.config.backends):
            st = replicas.get(b.key)
            age = (
                None
                if st is None or st.last_ok_s is None
                else round(now - st.last_ok_s, 3)
            )
            rows.append(
                {
                    "backend": b.key,
                    # never scraped OR last good scrape too old -> stale;
                    # the last-known signals ride along either way so a
                    # router can discount rather than forget
                    "stale": age is None or age > self.stale_after_s,
                    "age_s": age,
                    "scrapes_ok": 0 if st is None else st.scrapes_ok,
                    "scrape_failures": 0 if st is None else st.scrape_failures,
                    "consecutive_failures": (
                        0 if st is None else st.consecutive_failures
                    ),
                    "signals": {} if st is None else dict(st.signals),
                    "stats": {} if st is None else dict(st.stats_sections),
                    "balancer": balancer_state.get(b.key, {}),
                }
            )
        return {
            "interval_s": self.interval_s,
            "stale_after_s": self.stale_after_s,
            "scrape_rounds": self.scrape_rounds,
            "replicas": rows,
        }

    def federated_lines(self) -> list:
        """Prometheus text lines re-emitting every replica's scraped
        samples with a ``replica="host:port"`` label — appended to the
        gateway's own ``/metrics`` body. TYPE lines are grouped per family
        (a family may appear on several replicas but must be declared
        once). Stale replicas' last-known samples still federate; the
        paired ``dlt_fleet_replica_stale`` / ``_age_seconds`` gauges are
        the freshness signal consumers must join against."""
        from ..runtime.tracing import prom_line  # stdlib-only module

        with self._lock:
            replicas = [
                (k, list(st.samples), dict(st.types))
                for k, st in self._replicas.items()
            ]
        lines: list = []
        declared: set = set()
        meta: list = []  # (key, stale, age) freshness gauges
        now = now_s()
        for key, samples, types in replicas:
            for name, labels, value in samples:
                family = name
                for suffix in ("_bucket", "_sum", "_count"):
                    if name.endswith(suffix) and name[: -len(suffix)] in types:
                        family = name[: -len(suffix)]
                        break
                if family not in declared:
                    declared.add(family)
                    lines.append(
                        f"# TYPE {family} {types.get(family, 'untyped')}"
                    )
                lab = dict(labels)
                lab["replica"] = key
                val = int(value) if value == int(value) else value
                lines.append(prom_line(name, lab, val))
        with self._lock:
            for key, st in self._replicas.items():
                age = None if st.last_ok_s is None else now - st.last_ok_s
                stale = age is None or age > self.stale_after_s
                meta.append((key, stale, age))
        if meta:
            lines.append("# TYPE dlt_fleet_replica_stale gauge")
            for key, stale, _ in meta:
                lines.append(
                    prom_line("dlt_fleet_replica_stale", {"replica": key}, int(stale))
                )
            lines.append("# TYPE dlt_fleet_replica_age_seconds gauge")
            for key, _, age in meta:
                if age is not None:
                    lines.append(
                        prom_line(
                            "dlt_fleet_replica_age_seconds",
                            {"replica": key},
                            round(age, 3),
                        )
                    )
        return lines


def fetch_backend_configs(balancer, timeout_s: float | None = None) -> dict:
    """Live per-backend ``/debug/config`` fetch for the gateway's own
    ``/debug/config`` view — best-effort, one bounded round trip each,
    fanned out in parallel so a fleet of dead replicas costs ONE timeout,
    not backends×timeout (this endpoint matters most mid-outage). A dead
    backend contributes an ``{"error": ...}`` row, never a failure.
    `timeout_s=None` uses the attached scraper's configured timeout
    (``--fleet-timeout-s``), falling back to the module default."""
    if timeout_s is None:
        fleet = getattr(balancer, "fleet", None)
        timeout_s = fleet.timeout_s if fleet is not None else DEFAULT_TIMEOUT_S
    backends = list(balancer.config.backends)
    out = {}

    def fetch(b):
        try:
            status, body = http_get_text(b.host, b.port, "/debug/config", timeout_s)
            out[b.key] = (
                json.loads(body)
                if status == 200
                else {"error": f"/debug/config returned {status}"}
            )
        except Exception as e:
            out[b.key] = {"error": f"unreachable: {e}"}

    threads = [
        threading.Thread(target=fetch, args=(b,), daemon=True) for b in backends
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s + 1.0)
    for b in backends:  # a hung join still yields a row, never a KeyError
        out.setdefault(b.key, {"error": "timed out"})
    return out
