"""Serving layer: OpenAI-compatible HTTP API + load-balancer gateway.

Python re-implementations of the reference's hand-rolled C++ servers
(reference: src/dllama-api.cpp, src/dllama-gateway.cpp) with the same wire
behavior: `/v1/chat/completions` (stream + non-stream), `/v1/models`, the
naive KV-prefix cache across chat turns, and least-inflight backend
selection with failure cooldown.
"""


def parse_query(query: str) -> dict:
    """Parse an already-split query string (``a=1&b=2``) into a dict — the
    one copy both servers' control endpoints share. No URL-decoding: the
    only consumers are our own hex trace ids and backend keys."""
    return dict(kv.split("=", 1) for kv in query.split("&") if "=" in kv)
