"""Serving layer: OpenAI-compatible HTTP API + load-balancer gateway.

Python re-implementations of the reference's hand-rolled C++ servers
(reference: src/dllama-api.cpp, src/dllama-gateway.cpp) with the same wire
behavior: `/v1/chat/completions` (stream + non-stream), `/v1/models`, the
naive KV-prefix cache across chat turns, and least-inflight backend
selection with failure cooldown.
"""
