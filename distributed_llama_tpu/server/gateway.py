"""Load-balancer gateway: reverse proxy over dllama-api replicas.

Behavior-parity port of the reference gateway (reference:
src/dllama-gateway.cpp):

* backend selection: among healthy backends under their inflight cap, pick
  least-inflight, tie-broken by a round-robin cursor
  (selectBackendAndAcquire, dllama-gateway.cpp:266-301);
* a failed backend is marked unhealthy for `health_retry_ms` and routed
  around (releaseBackend, dllama-gateway.cpp:303-316);
* all backends busy -> 429; backend I/O failure -> 502;
* thread-per-connection, streaming the backend response through unchanged
  (SSE included).

On TPU serving this is the data-parallel axis: each backend is an
independent engine replica (one chip or one mesh), exactly like the
reference's replica-level DP (SURVEY.md §2 "DP / replica parallel").
"""

from __future__ import annotations

import argparse
import socket
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Backend:
    host: str
    port: int
    inflight: int = 0
    unhealthy_until: float = 0.0


@dataclass
class GatewayConfig:
    backends: list
    max_inflight_per_backend: int = 4
    health_retry_ms: int = 3000
    connect_timeout_s: float = 5.0
    # bounded wait queue: when every backend is saturated, up to queue_size
    # requests wait (max queue_timeout_s) for capacity before 429 — the
    # reference queues to a cap first too (dllama-gateway.cpp:332-373)
    queue_size: int = 16
    queue_timeout_s: float = 30.0


class Balancer:
    def __init__(self, config: GatewayConfig):
        self.config = config
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.rr_cursor = 0
        # FIFO wait queue of tickets: freed slots go to the head waiter, and
        # new arrivals queue behind existing waiters instead of stealing
        # capacity from them (without this, sustained load can starve queued
        # requests into 429 timeouts while latecomers sail through)
        self._queue: list[int] = []
        self._next_ticket = 0

    def _select_locked(self) -> int:
        now = time.monotonic()
        n = len(self.config.backends)
        selected, min_inflight = -1, None
        for i in range(n):
            idx = (self.rr_cursor + i) % n
            b = self.config.backends[idx]
            if b.unhealthy_until > now:
                continue
            if b.inflight >= self.config.max_inflight_per_backend:
                continue
            if min_inflight is None or b.inflight < min_inflight:
                min_inflight = b.inflight
                selected = idx
        if selected >= 0:
            self.config.backends[selected].inflight += 1
            self.rr_cursor = (selected + 1) % n
        return selected

    def acquire(self) -> int:
        """Returns backend index, or -1 when every backend is saturated AND
        the wait queue is full (or the queued wait timed out)."""
        with self.cond:
            # fast path only when nobody is already waiting — otherwise this
            # caller must take its place at the back of the line
            if not self._queue:
                idx = self._select_locked()
                if idx >= 0:
                    return idx
            if len(self._queue) >= self.config.queue_size:
                return -1  # queue full -> immediate 429
            ticket = self._next_ticket
            self._next_ticket += 1
            self._queue.append(ticket)
            try:
                deadline = time.monotonic() + self.config.queue_timeout_s
                while True:
                    # only the head of the line may claim capacity
                    if self._queue[0] == ticket:
                        idx = self._select_locked()
                        if idx >= 0:
                            return idx
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return -1
                    # short wait slices so an unhealthy backend coming back
                    # (a timed event no release() announces) is picked up
                    self.cond.wait(min(remaining, 0.25))
            finally:
                self._queue.remove(ticket)
                # the next waiter may have become head — wake everyone (the
                # queue is small, bounded by queue_size)
                self.cond.notify_all()

    def release(self, idx: int, mark_unhealthy: bool):
        if idx < 0:
            return
        with self.cond:
            b = self.config.backends[idx]
            if b.inflight > 0:
                b.inflight -= 1
            if mark_unhealthy:
                b.unhealthy_until = time.monotonic() + self.config.health_retry_ms / 1000.0
            self.cond.notify_all()


def _read_http_request(sock: socket.socket) -> bytes | None:
    """Read one full HTTP request (headers + Content-Length body)."""
    sock.settimeout(30)
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(16384)
        if not chunk:
            return None if not data else data
        data += chunk
    head, _, rest = data.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n")[1:]:
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":", 1)[1].strip())
    while len(rest) < length:
        chunk = sock.recv(16384)
        if not chunk:
            break
        rest += chunk
    # force Connection: close on the upstream leg — the proxy streams until
    # EOF, so a keep-alive backend response would hang it (clients sending
    # keep-alive, e.g. curl, would otherwise stall here)
    lines = [l for l in head.split(b"\r\n") if not l.lower().startswith(b"connection:")]
    lines.append(b"Connection: close")
    return b"\r\n".join(lines) + b"\r\n\r\n" + rest


def _plain_response(sock: socket.socket, code: int, text: str, body: str):
    payload = body.encode()
    resp = (
        f"HTTP/1.1 {code} {text}\r\n"
        "Content-Type: application/json; charset=utf-8\r\n"
        "Connection: close\r\n"
        f"Content-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload
    try:
        sock.sendall(resp)
    except OSError:
        pass


def handle_client(client: socket.socket, balancer: Balancer):
    config = balancer.config
    backend_idx = -1
    try:
        request = _read_http_request(client)
        if not request:
            return
        backend_idx = balancer.acquire()
        if backend_idx < 0:
            _plain_response(client, 429, "Too Many Requests", '{"error":"all backends busy"}')
            return
        b = config.backends[backend_idx]
        failed = False
        forwarded = False
        try:
            with socket.create_connection(
                (b.host, b.port), timeout=config.connect_timeout_s
            ) as upstream:
                upstream.sendall(request)
                upstream.settimeout(600)
                while True:
                    chunk = upstream.recv(16384)
                    if not chunk:
                        break
                    client.sendall(chunk)
                    forwarded = True
        except OSError:
            failed = True
            # only emit a 502 if nothing was forwarded yet — appending a
            # second status line to a partially streamed response would
            # corrupt the client's stream; mid-stream failures surface as EOF
            if not forwarded:
                _plain_response(client, 502, "Bad Gateway", '{"error":"backend failure"}')
        balancer.release(backend_idx, mark_unhealthy=failed)
        backend_idx = -1
    finally:
        if backend_idx >= 0:
            balancer.release(backend_idx, mark_unhealthy=False)
        try:
            client.close()
        except OSError:
            pass


def serve(port: int, balancer: Balancer) -> socket.socket:
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", port))
    srv.listen(64)
    return srv


def run(port: int, balancer: Balancer, stop_event: threading.Event | None = None):
    srv = serve(port, balancer)
    srv.settimeout(0.5)
    print(f"⚖️ Gateway listening on {port} -> {len(balancer.config.backends)} backends")
    while stop_event is None or not stop_event.is_set():
        try:
            client, _ = srv.accept()
        except socket.timeout:
            continue
        threading.Thread(target=handle_client, args=(client, balancer), daemon=True).start()
    srv.close()


def parse_backend(s: str) -> Backend:
    host, port = s.rsplit(":", 1)
    return Backend(host, int(port))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dllama-gateway")
    p.add_argument("--port", type=int, default=9999)
    p.add_argument("--backend", action="append", required=True, help="host:port (repeatable)")
    p.add_argument("--max-inflight-per-backend", type=int, default=4)
    p.add_argument("--health-retry-ms", type=int, default=3000)
    p.add_argument("--queue-size", type=int, default=16)
    p.add_argument("--queue-timeout-s", type=float, default=30.0)
    args = p.parse_args(argv)
    config = GatewayConfig(
        backends=[parse_backend(b) for b in args.backend],
        max_inflight_per_backend=args.max_inflight_per_backend,
        health_retry_ms=args.health_retry_ms,
        queue_size=args.queue_size,
        queue_timeout_s=args.queue_timeout_s,
    )
    run(args.port, Balancer(config))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
