"""Load-balancer gateway: resilient reverse proxy over dllama-api replicas.

Started as a behavior-parity port of the reference gateway (reference:
src/dllama-gateway.cpp:266-373) and grew the fault-tolerance layer the
reference's fixed 3s blackout only gestures at:

* backend selection: **cache-aware routing** by default (server/router.py,
  ``--router``/``DLT_ROUTER``) — shared-prefix chat traffic lands on the
  replica whose radix prefix cache already holds the prefix, scored
  against the fleet signal table (staleness-discounted), with decisions
  counted by reason on ``/metrics``; anything the router abstains from
  (non-chat routes, saturated favorites, policy least_inflight) falls to
  the reference selection: among assignable backends under their inflight
  cap, pick least-inflight, tie-broken by a round-robin cursor —
  closed-breaker backends preferred over half-open ones
  (selectBackendAndAcquire, dllama-gateway.cpp:266-301);
* **circuit breaker** per backend: `breaker_failure_threshold` consecutive
  failures OPEN the breaker (exponential backoff, capped at
  `breaker_backoff_max_s`); once the backoff elapses the breaker goes
  HALF_OPEN and admits exactly one trial (a prober health check or one
  client request) — success closes it, failure re-opens with doubled
  backoff. This replaces the old fixed `health_retry_ms` blackout;
* **active health probes**: a background prober thread hits each backend's
  ``GET /health`` on `probe_interval_s`, so a dead backend is discovered
  (and a recovering one re-admitted) without sacrificing client requests;
* **zero-byte retry**: a request whose upstream failed before ANY response
  byte was forwarded to the client is transparently retried on a different
  backend (bounded by `retry_attempts`, excluding backends already tried).
  Mid-stream failures still surface as EOF — appending a second status
  line to a half-streamed response would corrupt the client's stream;
* **load shedding**: when no backend is even conceptually routable (every
  breaker open or every backend draining), requests are shed immediately
  with ``503 + Retry-After`` instead of burning the full `queue_timeout_s`
  in the wait queue; saturated-but-healthy still queues and 429s;
* **control endpoints**: ``GET /gateway/stats`` (per-backend inflight,
  breaker state, failure/retry counters, queue depth) and
  ``POST /gateway/drain?backend=host:port`` / ``undrain`` — draining stops
  new assignments while inflight requests finish;
* **fleet signal plane** (server/fleet.py): a background scraper polls
  each backend's ``/metrics`` + ``/stats``, maintaining a per-replica
  signal table (prefix-hit rate, KV-pool headroom, batcher occupancy,
  SLO attainment, goodput, staleness) served at ``GET /gateway/fleet``
  and federated into the gateway's ``/metrics`` with ``replica=...``
  labels — the routing inputs a prefix-cache-aware balancer scores;
  ``GET /debug/config`` returns the resolved gateway config plus every
  backend's own config snapshot, proxied per-replica;
* thread-per-connection, streaming the backend response through unchanged
  (SSE included).

On TPU serving this is the data-parallel axis: each backend is an
independent engine replica (one chip or one mesh), exactly like the
reference's replica-level DP (SURVEY.md §2 "DP / replica parallel").
"""

from __future__ import annotations

import argparse
import json
import math
import os
import socket
import threading
import time
from dataclasses import dataclass, field

# stdlib-only import: runtime/__init__ lazies its engine exports, so the
# gateway stays runnable on a box with no jax installed
from ..runtime.tracing import (
    Hist,
    PROM_CONTENT_TYPE,
    SAMPLED_HEADER,
    TRACE_HEADER,
    TRACER,
    last_flight_record,
    now_us,
    parse_sampled,
    prom_line,
    render_counters,
    render_gauges,
    render_hist,
    to_us,
    trace_payload,
)
from . import parse_query

# stdlib-only siblings (the gateway must run on a jax-free box): the
# poison-quarantine ledger + fingerprinting, the chat-body hash-text
# builder, and the deadline resolution the retry loop stamps per attempt
from .quarantine import QuarantineLedger, fp_hex, request_fingerprint
from .router import (
    PREFETCH_CHAIN_HEADER,
    chain_header_value,
    messages_prefix_text,
)
from .scheduler import DEADLINE_ENVS, DEADLINE_HEADER, resolve_deadline_ms

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


@dataclass
class Backend:
    host: str
    port: int
    inflight: int = 0
    draining: bool = False
    # -- circuit breaker state (mutated only under the Balancer lock) --
    breaker: str = BREAKER_CLOSED
    consecutive_failures: int = 0
    open_until: float = 0.0  # monotonic deadline while OPEN
    backoff_s: float = 0.0  # current backoff (0 = next open uses the initial)
    # HALF_OPEN single-trial slot: None = free, "probe" = the prober owns
    # it, "request" = a client request owns it. A request-trial is only
    # admitted when inflight == 0, so while trial_kind == "request" the ONE
    # inflight request IS the trial — release() can attribute the outcome
    # without per-request identity
    trial_kind: str | None = None
    # -- counters (observability; monotonic) --
    n_served: int = 0
    n_failures: int = 0
    n_retries_away: int = 0  # zero-byte failures retried onto another backend
    n_breaker_opens: int = 0
    n_probes_ok: int = 0
    n_probes_failed: int = 0

    @property
    def key(self) -> str:
        return f"{self.host}:{self.port}"


@dataclass
class GatewayConfig:
    backends: list
    max_inflight_per_backend: int = 4
    connect_timeout_s: float = 5.0
    # upstream read timeout: a backend that accepts but never answers (the
    # slow-loris failure mode) is treated as failed — with zero bytes
    # forwarded that means a transparent retry, not a hung client
    upstream_read_timeout_s: float = 600.0
    # bounded wait queue: when every backend is saturated, up to queue_size
    # requests wait (max queue_timeout_s) for capacity before 429 — the
    # reference queues to a cap first too (dllama-gateway.cpp:332-373)
    queue_size: int = 16
    queue_timeout_s: float = 30.0
    # circuit breaker: this many CONSECUTIVE failures open the breaker for
    # breaker_backoff_s, doubling per re-open up to breaker_backoff_max_s
    breaker_failure_threshold: int = 3
    breaker_backoff_s: float = 1.0
    breaker_backoff_max_s: float = 30.0
    # active prober: <= 0 disables (unit tests drive the breaker directly)
    probe_interval_s: float = 2.0
    probe_timeout_s: float = 2.0
    probe_path: str = "/health"
    # zero-byte retry: how many ADDITIONAL backends to try after a failure
    # that forwarded nothing to the client
    retry_attempts: int = 2
    # legacy knob (the old fixed blackout). When set, it seeds the breaker's
    # INITIAL backoff so old call sites keep their intent: "don't re-admit a
    # failed backend for N ms" becomes the first open interval.
    health_retry_ms: int | None = None
    # fleet signal plane (server/fleet.py): per-replica /metrics + /stats
    # scrape cadence feeding /gateway/fleet and the federated /metrics
    # rollup. None resolves the DLT_FLEET_SCRAPE_S env (default 2 s);
    # <= 0 disables the scraper thread (control endpoints still answer,
    # reporting every replica as never-scraped/stale).
    fleet_scrape_s: float | None = None
    fleet_timeout_s: float | None = None
    # cache-aware routing (server/router.py): None resolves DLT_ROUTER
    # (default cache_aware); "least_inflight" keeps the legacy selection
    # (the A/B arm the routing bench compares against)
    router_policy: str | None = None
    # poison-request quarantine (server/quarantine.py): strike limit before
    # a fingerprint stops being retried and 422s terminally. None resolves
    # DLT_QUARANTINE_STRIKES (default 2); <= 0 disables the ledger entirely
    # (the fault-injection harness pins 0 — seeded fault plans deliberately
    # fail the same body many times and must keep their retry semantics)
    quarantine_strikes: int | None = None
    # goodput-driven autoscaler (server/autoscaler.py): evaluation-tick
    # cadence. None resolves DLT_AUTOSCALE_S (default 0 = OFF — capacity
    # decisions are opt-in); > 0 attaches the control loop that drains /
    # undrains replicas on fleet goodput headroom with warm handoff.
    autoscale_s: float | None = None
    # active-active gateway peering (server/peering.py): addresses of the
    # OTHER gateways serving this fleet (--peer-gateway, repeatable; full
    # mesh — events are not relayed). Empty/None = solo gateway.
    peer_gateways: list | None = None
    # gossip tick cadence; None resolves DLT_GW_PEER_SYNC_S (default 2 s);
    # <= 0 attaches peering (the /gateway/peer/sync endpoint answers, the
    # receive path applies) without the background push thread
    peer_sync_s: float | None = None
    # this gateway's identity for LWW origins + leader election; None
    # resolves to "<hostname>:<port>" at serve time (stable across a
    # same-box restart — a restarted gateway re-enters the live set under
    # its old id instead of minting a zombie elector)
    gateway_id: str | None = None
    # crash-only warm restart (server/recovery.py): rebuild the locality
    # map / quarantine ledger / drain state from the fleet before taking
    # traffic. None resolves DLT_GW_RECOVER (default on); everything is
    # best-effort — a fleet that answers nothing yields a cold start.
    recover_on_start: bool | None = None

    def __post_init__(self):
        if self.health_retry_ms is not None:
            self.breaker_backoff_s = self.health_retry_ms / 1000.0
            self.breaker_backoff_max_s = max(
                self.breaker_backoff_max_s, self.breaker_backoff_s
            )


class Balancer:
    # acquire() sentinels
    BUSY = -1  # saturated (queue full or queued wait timed out) -> 429
    SHED = -2  # no routable backend at all (breakers open / draining) -> 503

    def __init__(self, config: GatewayConfig):
        self.config = config
        # fleet signal plane (server/fleet.py FleetScraper): attached by
        # run() — or directly by tests — so the control endpoints can serve
        # /gateway/fleet and the federated /metrics rollup. None = scraping
        # disabled; both endpoints degrade gracefully.
        self.fleet = None
        # cache-aware router (server/router.py Router): attached by run()
        # — or directly by tests. None = least-inflight only (the legacy
        # selection path, byte-for-byte unchanged).
        self.router = None
        # goodput-driven autoscaler (server/autoscaler.py Autoscaler):
        # attached by run() when autoscale_s > 0 — or directly by tests.
        # None = no capacity control loop (the default).
        self.autoscaler = None
        # active-active peering (server/peering.py GatewayPeering):
        # attached by GatewayServer when peer_gateways is non-empty.
        # None = solo gateway (no gossip, no leader gating).
        self.peering = None
        # warm-restart recovery record (server/recovery.py): set once at
        # startup when recovery ran; rendered as dlt_gateway_recovery_*
        # and the /gateway/fleet "recovery" section.
        self.recovery = None
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.rr_cursor = 0
        # FIFO wait queue of tickets: freed slots go to the head waiter, and
        # new arrivals queue behind existing waiters instead of stealing
        # capacity from them (without this, sustained load can starve queued
        # requests into 429 timeouts while latecomers sail through)
        self._queue: list[int] = []
        self._next_ticket = 0
        # per-request gateway wall-time histogram (cumulative log buckets;
        # the /metrics twin of the backend's TTFT/per-token histograms)
        self.request_ms = Hist()
        # poison-request quarantine (server/quarantine.py): the strike
        # ledger the retry loop consults before replaying a failed body
        # into yet another replica. None = disabled (quarantine_strikes<=0).
        qs = config.quarantine_strikes
        if qs is not None and qs <= 0:
            self.quarantine = None
        else:
            self.quarantine = QuarantineLedger(limit=qs)
        # gateway-level counters (under the lock)
        self.counters = {
            "requests": 0,
            "proxied_ok": 0,
            "zero_byte_retries": 0,
            "midstream_failures": 0,
            "rejected_429": 0,
            "shed_503": 0,
            "bad_gateway_502": 0,
            "quarantined_422": 0,   # poison fingerprints refused terminally
            "poison_strikes": 0,    # implication events the ledger recorded
            # transport deaths NOT struck because the fleet already knew
            # the backend was sick (breaker open, stale scrape, draining)
            # — the correlated-death false-positive the discount removes
            "poison_strikes_discounted": 0,
            "deadline_504": 0,      # requests whose deadline died in-house
        }

    def count(self, name: str, n: int = 1):
        with self.lock:
            self.counters[name] = self.counters.get(name, 0) + n

    # -- breaker transitions (call under self.lock) -------------------------

    def _maybe_half_open_locked(self, b: Backend, now: float):
        if b.breaker == BREAKER_OPEN and now >= b.open_until:
            b.breaker = BREAKER_HALF_OPEN
            b.trial_kind = None

    def _record_success_locked(self, b: Backend):
        b.consecutive_failures = 0
        b.backoff_s = 0.0
        if b.breaker != BREAKER_CLOSED:
            b.breaker = BREAKER_CLOSED
            b.trial_kind = None

    def _record_failure_locked(self, b: Backend, now: float):
        b.consecutive_failures += 1
        b.n_failures += 1
        if b.breaker == BREAKER_OPEN:
            # already open: a STALE failure (a request admitted before the
            # breaker opened, finishing late) must not extend or double the
            # backoff — escalation is driven by half-open trial outcomes
            return
        if (
            b.breaker == BREAKER_HALF_OPEN
            or b.consecutive_failures >= self.config.breaker_failure_threshold
        ):
            b.backoff_s = (
                self.config.breaker_backoff_s
                if b.backoff_s <= 0
                else min(b.backoff_s * 2, self.config.breaker_backoff_max_s)
            )
            b.open_until = now + b.backoff_s
            b.breaker = BREAKER_OPEN
            b.trial_kind = None
            b.n_breaker_opens += 1

    def _assignable_locked(self, b: Backend, now: float) -> bool:
        """May this backend receive a NEW client request right now?"""
        if b.draining or b.inflight >= self.config.max_inflight_per_backend:
            return False
        self._maybe_half_open_locked(b, now)
        if b.breaker == BREAKER_OPEN:
            return False
        if b.breaker == BREAKER_HALF_OPEN:
            # exactly one trial at a time, and only onto an otherwise-idle
            # backend — leftover pre-open inflight requests would make the
            # trial's outcome unattributable at release time
            return b.trial_kind is None and b.inflight == 0
        return True

    def _routable_in_principle_locked(self, exclude, now: float) -> bool:
        """Is there any point waiting? True when some backend could take the
        request once capacity frees (closed breaker, or a half-open trial in
        flight that may succeed). All-open/all-draining means waiting burns
        queue_timeout_s for nothing -> shed with 503."""
        for i, b in enumerate(self.config.backends):
            if i in exclude or b.draining:
                continue
            self._maybe_half_open_locked(b, now)
            if b.breaker != BREAKER_OPEN:
                return True
        return False

    def retry_after_hint_s(self) -> float:
        """Seconds until the earliest open breaker re-admits a trial."""
        with self.lock:
            now = time.monotonic()
            deadlines = [
                b.open_until - now
                for b in self.config.backends
                if b.breaker == BREAKER_OPEN and not b.draining
            ]
        return max(0.0, min(deadlines)) if deadlines else 1.0

    def _select_locked(self, exclude=frozenset(), prefer=None) -> int:
        now = time.monotonic()
        n = len(self.config.backends)
        # router preference (server/router.py): try the ranked candidates
        # in order first — but only onto CLOSED breakers (a half-open trial
        # is a probe slot, not a cache-affinity opportunity; the default
        # path below still admits it when nothing preferred is assignable)
        if prefer:
            for idx in prefer:
                if idx < 0 or idx >= n or idx in exclude:
                    continue
                b = self.config.backends[idx]
                if b.breaker == BREAKER_CLOSED and self._assignable_locked(b, now):
                    b.inflight += 1
                    self.rr_cursor = (idx + 1) % n
                    return idx
        selected, best = -1, None
        for i in range(n):
            idx = (self.rr_cursor + i) % n
            b = self.config.backends[idx]
            if idx in exclude or not self._assignable_locked(b, now):
                continue
            # closed breakers beat half-open trials; a backend with PENDING
            # consecutive failures (below the breaker threshold) only gets
            # traffic when clean backends are saturated — without this, a
            # black-holing backend (connect timeouts, inflight always 0)
            # stays the least-inflight favorite and every request burns a
            # connect timeout until the breaker finally opens; then
            # least-inflight
            score = (
                0 if b.breaker == BREAKER_CLOSED else 1,
                1 if b.consecutive_failures > 0 else 0,
                b.inflight,
            )
            if best is None or score < best:
                best = score
                selected = idx
        if selected >= 0:
            b = self.config.backends[selected]
            b.inflight += 1
            if b.breaker == BREAKER_HALF_OPEN:
                b.trial_kind = "request"
            self.rr_cursor = (selected + 1) % n
        return selected

    def acquire(self, exclude=frozenset(), prefer=None) -> int:
        """Returns a backend index, or BUSY (-1) when every backend is
        saturated AND the wait queue is full (or the queued wait timed out),
        or SHED (-2) when no backend is routable at all (every breaker open
        or every backend draining) — the caller should 503 immediately.
        `prefer` (server/router.py RoutePlan.ranked) biases selection: the
        ranked candidates are tried in order before the least-inflight
        fallback, and a queued waiter keeps its preference for when it
        reaches the head of the line."""
        exclude = frozenset(exclude)
        with self.cond:
            if not self._routable_in_principle_locked(exclude, time.monotonic()):
                return self.SHED
            # fast path only when nobody is already waiting — otherwise this
            # caller must take its place at the back of the line
            if not self._queue:
                idx = self._select_locked(exclude, prefer)
                if idx >= 0:
                    return idx
            if exclude:
                # a zero-byte retry is opportunistic: it must NOT join the
                # FIFO queue, where its exclude set would sit at the head
                # idling capacity on its excluded backend (only the head may
                # claim, and tickets don't carry excludes) while waiters
                # behind it could have used that slot
                return self.BUSY
            if len(self._queue) >= self.config.queue_size:
                return self.BUSY  # queue full -> immediate 429
            ticket = self._next_ticket
            self._next_ticket += 1
            self._queue.append(ticket)
            try:
                deadline = time.monotonic() + self.config.queue_timeout_s
                while True:
                    # only the head of the line may claim capacity
                    if self._queue[0] == ticket:
                        idx = self._select_locked(exclude, prefer)
                        if idx >= 0:
                            return idx
                    now = time.monotonic()
                    # conditions changed mid-wait? (breaker opened on the
                    # last healthy backend) -> shed instead of burning the
                    # remaining timeout
                    if not self._routable_in_principle_locked(exclude, now):
                        return self.SHED
                    remaining = deadline - now
                    if remaining <= 0:
                        return self.BUSY
                    # short wait slices so a timed event no release()
                    # announces — a breaker's backoff elapsing into
                    # half-open — is picked up mid-wait
                    self.cond.wait(min(remaining, 0.25))
            finally:
                self._queue.remove(ticket)
                # the next waiter may have become head — wake everyone (the
                # queue is small, bounded by queue_size)
                self.cond.notify_all()

    def release(self, idx: int, mark_unhealthy: bool):
        if idx < 0:
            return
        with self.cond:
            b = self.config.backends[idx]
            if b.inflight > 0:
                b.inflight -= 1
            # the admission precondition (trial only onto an idle backend)
            # makes the sole inflight request the trial — this release
            # resolves it. A "probe" trial is resolved only by record_probe;
            # an old request completing must not clear it
            was_trial = b.trial_kind == "request"
            if was_trial:
                b.trial_kind = None
            if mark_unhealthy:
                self._record_failure_locked(b, time.monotonic())
            else:
                b.n_served += 1
                if was_trial or b.breaker == BREAKER_CLOSED:
                    self._record_success_locked(b)
                # else: a STALE success — a request admitted before the
                # breaker opened, finishing late. It must not close an open
                # breaker and zero the backoff escalation; re-admission goes
                # through the attributed half-open trial
            self.cond.notify_all()

    # -- prober interface ---------------------------------------------------

    def claim_probe(self, idx: int) -> bool:
        """May the prober check this backend now? CLOSED backends are always
        checkable (proactive death detection); OPEN ones only once their
        backoff elapsed — the probe then becomes the half-open trial."""
        with self.lock:
            b = self.config.backends[idx]
            self._maybe_half_open_locked(b, time.monotonic())
            if b.breaker == BREAKER_CLOSED:
                # only probe IDLE closed backends: a serialized (batch=1)
                # replica handles one connection at a time, so a probe
                # racing a long completion would time out and open the
                # breaker on a healthy-but-busy backend. With requests in
                # flight, their outcomes are the health signal
                return b.inflight == 0
            if b.breaker == BREAKER_HALF_OPEN and b.trial_kind is None:
                b.trial_kind = "probe"
                return True
            return False

    def record_probe(self, idx: int, ok: bool):
        with self.cond:
            b = self.config.backends[idx]
            was_trial = b.trial_kind == "probe"
            if was_trial:
                b.trial_kind = None
            if ok:
                b.n_probes_ok += 1
                if was_trial or b.breaker == BREAKER_CLOSED:
                    self._record_success_locked(b)
                # else: the breaker opened while this (pre-open) probe was in
                # flight — stale evidence, leave re-admission to a fresh trial
            else:
                if not was_trial and b.breaker == BREAKER_CLOSED and b.inflight > 0:
                    # ambiguous timeout: a request was assigned after the
                    # idle-claim and a serialized backend answers one
                    # connection at a time — that request's outcome is the
                    # health signal, not this probe's
                    pass
                else:
                    b.n_probes_failed += 1
                    self._record_failure_locked(b, time.monotonic())
            self.cond.notify_all()

    # -- operator controls --------------------------------------------------

    def _find(self, key: str) -> int:
        for i, b in enumerate(self.config.backends):
            if b.key == key:
                return i
        return -1

    def set_draining(self, key: str, draining: bool, by: str = "operator",
                     record: bool = True, notify: bool = True) -> bool:
        """Flip one backend's draining flag. ``by`` tags the actuator
        (operator endpoint vs autoscaler — the tag rides the replica's
        drain hint and the peering event, so a restarted gateway restores
        the right ownership). ``record=False`` suppresses the peering
        event (applying a PEER's event must not re-broadcast it);
        ``notify=False`` suppresses the replica drain-hint POST (recovery
        just READ the hint it would be posting)."""
        with self.cond:
            idx = self._find(key)
            if idx < 0:
                return False
            b = self.config.backends[idx]
            changed = b.draining != draining
            b.draining = draining
            remaining = [
                b.key for b in self.config.backends
                if not b.draining and b.key != key
            ]
            router = self.router
            autoscaler = self.autoscaler
            peering = self.peering
            self.cond.notify_all()
        if changed and record and peering is not None:
            peering.note_drain(key, draining, by)
        if changed and notify and self.fleet is not None:
            # crash-safety hint (server/recovery.py): the replica itself
            # remembers it is draining (and WHO drained it), so a gateway
            # restart reads the drain back from /health instead of
            # silently re-admitting a half-drained replica. Best-effort +
            # off-thread: a replica that cannot answer still drains here.
            # Fleet-blind gateways (no scraper) skip the hint — only the
            # fleet-aware recovery sweep would ever read it back, and the
            # extra POST would perturb scraping-off harnesses.
            host, port = self.config.backends[idx].host, \
                self.config.backends[idx].port
            threading.Thread(
                target=_notify_drain_hint,
                args=(host, port, draining, by,
                      self.config.probe_timeout_s),
                daemon=True, name="gateway-drain-hint",
            ).start()
        if draining and router is not None:
            # locality hygiene (server/router.py): learned chain keys must
            # not keep naming a home acquire() will never hand out again —
            # re-homed to surviving rendezvous owners (or purged when none).
            # OUTSIDE the balancer lock: the router takes its own lock, and
            # plan() holds it before touching ours (lock-order discipline).
            router.forget_backend(key, remaining)
        if not draining and autoscaler is not None:
            # ANY undrain (operator or control loop) clears the
            # autoscaler's drain ownership: a replica the operator later
            # re-drains for maintenance must never be auto-undrained on
            # the strength of a drain the loop did weeks ago
            autoscaler.forget(key)
        return True

    def reset_breaker(self, idx: int):
        """Force-close a breaker (operator/test override after a restart)."""
        with self.cond:
            self._record_success_locked(self.config.backends[idx])
            self.cond.notify_all()

    def stats(self) -> dict:
        with self.lock:
            now = time.monotonic()
            backends = []
            for b in self.config.backends:
                backends.append(
                    {
                        "backend": b.key,
                        "inflight": b.inflight,
                        "draining": b.draining,
                        "breaker": b.breaker,
                        "consecutive_failures": b.consecutive_failures,
                        "open_for_ms": max(0, int((b.open_until - now) * 1000))
                        if b.breaker == BREAKER_OPEN
                        else 0,
                        "served": b.n_served,
                        "failures": b.n_failures,
                        "retries_away": b.n_retries_away,
                        "breaker_opens": b.n_breaker_opens,
                        "probes_ok": b.n_probes_ok,
                        "probes_failed": b.n_probes_failed,
                    }
                )
            out = {
                "backends": backends,
                "queue_depth": len(self._queue),
                "counters": dict(self.counters),
            }
        # outside the balancer lock: the ledger has its own (lock-order
        # discipline — never nest foreign locks under ours)
        out["quarantine"] = (
            None if self.quarantine is None else self.quarantine.snapshot()
        )
        return out


class HealthProber(threading.Thread):
    """Background active prober: one ``GET /health`` per backend per
    interval. Probe outcomes drive the same breaker transitions as request
    outcomes, so a dead backend opens its breaker before any client lands on
    it and a recovered one is re-admitted via the half-open trial."""

    def __init__(self, balancer: Balancer, stop_event: threading.Event):
        super().__init__(daemon=True, name="gateway-prober")
        self.balancer = balancer
        self.stop_event = stop_event

    def probe_once(self):
        cfg = self.balancer.config
        for idx in range(len(cfg.backends)):
            if self.stop_event.is_set():
                return
            if not self.balancer.claim_probe(idx):
                continue
            b = cfg.backends[idx]
            ok = probe_health(
                b.host, b.port, cfg.probe_timeout_s, cfg.probe_path
            )
            self.balancer.record_probe(idx, ok)

    def run(self):
        interval = self.balancer.config.probe_interval_s
        while not self.stop_event.wait(interval):
            self.probe_once()


def probe_health(host: str, port: int, timeout_s: float, path: str = "/health") -> bool:
    """One health-check round trip; True iff the backend answered 200."""
    try:
        with socket.create_connection((host, port), timeout=timeout_s) as s:
            s.settimeout(timeout_s)
            s.sendall(
                f"GET {path} HTTP/1.1\r\nHost: {host}\r\n"
                "Connection: close\r\n\r\n".encode()
            )
            data = b""
            while b"\r\n" not in data:
                chunk = s.recv(1024)
                if not chunk:
                    break
                data += chunk
            parts = data.split(b"\r\n", 1)[0].split()
            return len(parts) >= 2 and parts[0].startswith(b"HTTP/") and parts[1] == b"200"
    except OSError:
        return False


def _notify_drain_hint(host: str, port: int, draining: bool, by: str,
                       timeout_s: float):
    """Best-effort ``POST /admin/drain_hint`` to a replica: the replica
    carries its own drain state (surfaced on ``/health``) so a warm
    -restarting gateway re-learns drains from the fleet instead of
    silently re-admitting a half-drained replica (server/recovery.py)."""
    from .fleet import http_post_json

    try:
        http_post_json(
            host, port, "/admin/drain_hint",
            {"draining": draining, "by": by}, timeout_s,
        )
    except Exception:
        pass  # dlt: allow(swallowed-exception) — the hint is advisory
        # redundancy for crash recovery; the drain itself already landed
        # on the gateway and (when peered) gossiped to every peer


def _strike_discount_reason(balancer: Balancer, idx: int) -> str | None:
    """Was this backend ALREADY known-sick when an attempt died on it?
    Returns the discount reason (or None = the death is honest strike
    evidence). A transport death on a backend the fleet had marked
    unhealthy — breaker not closed, fleet-table row stale, or draining
    (autoscaler/operator rolling restart) — implicates the BACKEND, not
    the request: striking it is how two correlated replica deaths used
    to terminally 422 an innocent conversation (the PR 14 documented
    trade-off, now closed). Checked at FAILURE time, not acquire time:
    the drain/open that matters is the one that landed while the request
    was in flight."""
    b = balancer.config.backends[idx]
    with balancer.lock:
        if b.draining:
            return "draining"
        if b.breaker != BREAKER_CLOSED:
            return "breaker"
    fleet = balancer.fleet
    if fleet is not None:
        row = fleet.router_signals().get(b.key)
        if row is not None and row.get("stale"):
            return "stale_scrape"
    return None


def _read_http_request(sock: socket.socket) -> bytes | None:
    """Read one full HTTP request (headers + Content-Length body)."""
    sock.settimeout(30)
    data = b""
    while b"\r\n\r\n" not in data:
        chunk = sock.recv(16384)
        if not chunk:
            return None if not data else data
        data += chunk
    head, _, rest = data.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n")[1:]:
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":", 1)[1].strip())
    while len(rest) < length:
        chunk = sock.recv(16384)
        if not chunk:
            break
        rest += chunk
    # force Connection: close on the upstream leg — the proxy streams until
    # EOF, so a keep-alive backend response would hang it (clients sending
    # keep-alive, e.g. curl, would otherwise stall here)
    lines = [l for l in head.split(b"\r\n") if not l.lower().startswith(b"connection:")]
    lines.append(b"Connection: close")
    return b"\r\n".join(lines) + b"\r\n\r\n" + rest


def _request_line(request: bytes) -> tuple[str, str]:
    """(method, path) from the raw request bytes; ("", "") if unparseable."""
    try:
        first = request.split(b"\r\n", 1)[0].decode("latin-1")
        method, path, _ = first.split(" ", 2)
        return method.upper(), path
    except ValueError:
        return "", ""


def _header_value(request: bytes, name: bytes) -> str | None:
    """Case-insensitive header lookup in raw request bytes."""
    head = request.split(b"\r\n\r\n", 1)[0]
    needle = name.lower() + b":"
    for line in head.split(b"\r\n")[1:]:
        if line.lower().startswith(needle):
            return line.split(b":", 1)[1].strip().decode("latin-1")
    return None


def _with_header(request: bytes, name: str, value: str) -> bytes:
    """Inject (or replace) one header in raw request bytes — the
    per-attempt re-stamping primitive (trace identity, sampling decision,
    and the deadline's REMAINING budget all shrink-or-ride per retry)."""
    head, _, rest = request.partition(b"\r\n\r\n")
    needle = (name.lower() + ":").encode()
    lines = [l for l in head.split(b"\r\n") if not l.lower().startswith(needle)]
    lines.insert(1, f"{name}: {value}".encode())
    return b"\r\n".join(lines) + b"\r\n\r\n" + rest


def _with_trace_header(request: bytes, trace_id: str, sampled: bool) -> bytes:
    """Inject (or replace) the X-DLT-Trace-Id and X-DLT-Trace-Sampled
    headers, so the backend sees the SAME id — and the SAME sampling
    decision — across the gateway's transparent retries: one
    coherently-sampled trace stitches gateway -> retry -> backend
    together (the two processes' 1-in-N counters are never in phase)."""
    request = _with_header(request, SAMPLED_HEADER, str(int(sampled)))
    return _with_header(request, TRACE_HEADER, trace_id)


def _respond_quarantined(client, balancer: Balancer, fp: int, hdrs: dict):
    """The terminal 422 a quarantined fingerprint earns — shared by the
    pre-routing check and the mid-retry engagement so the wire contract
    (and its counter) can never drift between the two sites."""
    balancer.count("quarantined_422")
    _plain_response(
        client, 422, "Unprocessable Entity",
        json.dumps({
            "error": "request quarantined: this conversation has "
            "repeatedly crashed or stalled replicas",
            "fingerprint": fp_hex(fp),
        }),
        headers=hdrs,
    )


def _plain_response(
    sock: socket.socket, code: int, text: str, body: str,
    headers: dict | None = None,
    ctype: str = "application/json; charset=utf-8",
):
    payload = body.encode()
    extra = "".join(f"{k}: {v}\r\n" for k, v in (headers or {}).items())
    resp = (
        f"HTTP/1.1 {code} {text}\r\n"
        f"Content-Type: {ctype}\r\n"
        "Connection: close\r\n"
        f"{extra}"
        f"Content-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload
    try:
        sock.sendall(resp)
    except OSError:
        pass


def render_gateway_metrics(balancer: Balancer) -> str:
    """The gateway's ``GET /metrics`` body: Prometheus text exposition of
    the balancer counters, queue depth, per-backend breaker/inflight state,
    and the per-request wall-time histogram — plus, when the fleet scraper
    is attached, the FEDERATED rollup: every replica's scraped samples
    re-emitted with a ``replica="host:port"`` label (server/fleet.py), so
    one scrape of the gateway sees the whole fleet."""
    s = balancer.stats()
    lines: list = []
    render_counters(lines, s["counters"], prefix="dlt_gateway")
    render_gauges(lines, {"queue_depth": s["queue_depth"]}, prefix="dlt_gateway")
    gauge_cols = (("inflight", "inflight"), ("draining", "draining"))
    for metric, col in gauge_cols:
        m = f"dlt_gateway_backend_{metric}"
        lines.append(f"# TYPE {m} gauge")
        for b in s["backends"]:
            lines.append(prom_line(m, {"backend": b["backend"]}, int(b[col])))
    m = "dlt_gateway_backend_breaker_open"
    lines.append(f"# TYPE {m} gauge")
    for b in s["backends"]:
        lines.append(
            prom_line(
                m, {"backend": b["backend"]},
                int(b["breaker"] == BREAKER_OPEN),
            )
        )
    counter_cols = (
        "served", "failures", "retries_away", "breaker_opens",
        "probes_ok", "probes_failed",
    )
    for col in counter_cols:
        m = f"dlt_gateway_backend_{col}_total"
        lines.append(f"# TYPE {m} counter")
        for b in s["backends"]:
            lines.append(prom_line(m, {"backend": b["backend"]}, b[col]))
    render_hist(lines, "dlt_gateway_request_ms", balancer.request_ms.snapshot())
    if balancer.router is not None:
        # routing decisions by reason (server/router.py): every known
        # reason always renders, zero-valued included, so dashboards never
        # see a series appear from nowhere mid-incident
        from .router import REASONS

        counts = balancer.router.decisions_snapshot()
        m = "dlt_router_decisions_total"
        lines.append(f"# TYPE {m} counter")
        for reason in REASONS:
            lines.append(prom_line(m, {"reason": reason}, counts.get(reason, 0)))
        # drain hygiene + warm handoff (server/router.py): the acceptance
        # signal that affinity was re-homed BEFORE a drained replica
        # disappeared — fleet prefix_hit_tokens recovering is the effect,
        # these counters are the cause
        h = balancer.router.handoff_snapshot()
        for name, col in (
            ("dlt_router_handoff_rehomed_keys_total", "rehomed_keys"),
            ("dlt_router_locality_purged_keys_total", "purged_keys"),
            ("dlt_router_drain_events_total", "drain_events"),
        ):
            lines.append(f"# TYPE {name} counter")
            lines.append(prom_line(name, None, h.get(col, 0)))
    if balancer.autoscaler is not None:
        lines.extend(balancer.autoscaler.metrics_lines())
    if balancer.peering is not None:
        # dlt_gw_peer_* (server/peering.py): sync outcomes, applied
        # events by kind, per-peer liveness, leadership
        lines.extend(balancer.peering.metrics_lines())
    if balancer.recovery is not None:
        # dlt_gateway_recovery_* (server/recovery.py): what the warm
        # restart re-learned from the fleet
        from .recovery import recovery_metrics_lines

        lines.extend(recovery_metrics_lines(balancer.recovery))
    if balancer.fleet is not None:
        lines.extend(balancer.fleet.federated_lines())
    return "\n".join(lines) + "\n"


def _handle_control(client: socket.socket, balancer: Balancer, method: str,
                    path: str, request: bytes = b""):
    """The gateway's own control + observability endpoints (never proxied;
    scrape backends' /metrics directly for engine-side numbers)."""
    route, _, query = path.partition("?")
    if route == "/gateway/stats" and method == "GET":
        _plain_response(client, 200, "OK", json.dumps(balancer.stats()))
        return
    if route == "/gateway/peer/sync" and method == "POST":
        # the peering receive path (server/peering.py): a peer gateway's
        # bounded delta — locality learns, strikes, drain events — applied
        # with LWW on monotonic event ids; the ack carries our id + clock
        # (the liveness signal leader election runs on)
        if balancer.peering is None:
            _plain_response(
                client, 404, "Not Found",
                '{"error":"peering not configured on this gateway"}',
            )
            return
        try:
            payload = json.loads(request.partition(b"\r\n\r\n")[2])
            if not isinstance(payload, dict):
                raise ValueError("not an object")
        except ValueError:
            _plain_response(client, 400, "Bad Request", '{"error":"bad json"}')
            return
        _plain_response(
            client, 200, "OK", json.dumps(balancer.peering.apply(payload))
        )
        return
    if route == "/gateway/fleet" and method == "GET":
        # per-replica signal table (server/fleet.py): routing signals +
        # staleness + breaker state joined from the balancer. With no
        # scraper attached the endpoint still answers (enabled: false)
        # so dashboards never 404-flap on a config change.
        if balancer.fleet is None:
            _plain_response(
                client, 200, "OK",
                json.dumps({
                    "enabled": False, "replicas": [],
                    "router": (
                        None if balancer.router is None
                        else balancer.router.snapshot()
                    ),
                    "autoscaler": (
                        None if balancer.autoscaler is None
                        else balancer.autoscaler.snapshot()
                    ),
                    "peering": (
                        None if balancer.peering is None
                        else balancer.peering.snapshot()
                    ),
                    "recovery": balancer.recovery,
                }),
            )
            return
        payload = dict(balancer.fleet.snapshot(), enabled=True)
        # router view (server/router.py): policy, per-reason decision
        # counts, locality-map occupancy — joined here so the routing view
        # and the signal table it scores can never disagree
        payload["router"] = (
            None if balancer.router is None else balancer.router.snapshot()
        )
        # autoscaler view (server/autoscaler.py): config, last decision,
        # per-action counts, handoff totals — same join rationale
        payload["autoscaler"] = (
            None if balancer.autoscaler is None
            else balancer.autoscaler.snapshot()
        )
        # peering view (server/peering.py): self/leader ids, live peers,
        # clock, pending deltas — and the warm-restart recovery record
        payload["peering"] = (
            None if balancer.peering is None
            else balancer.peering.snapshot()
        )
        payload["recovery"] = balancer.recovery
        _plain_response(client, 200, "OK", json.dumps(payload))
        return
    if route == "/debug/config" and method == "GET":
        # resolved gateway configuration + every backend's own
        # /debug/config proxied per-replica (fleet debugging without
        # shell access to any box). Backend fetches are bounded and
        # best-effort — a dead replica contributes an error row.
        from . import fleet as fleet_mod

        cfg = balancer.config
        payload = {
            "gateway": {
                "backends": [b.key for b in cfg.backends],
                "max_inflight_per_backend": cfg.max_inflight_per_backend,
                "queue_size": cfg.queue_size,
                "queue_timeout_s": cfg.queue_timeout_s,
                "breaker_failure_threshold": cfg.breaker_failure_threshold,
                "breaker_backoff_s": cfg.breaker_backoff_s,
                "breaker_backoff_max_s": cfg.breaker_backoff_max_s,
                "probe_interval_s": cfg.probe_interval_s,
                "retry_attempts": cfg.retry_attempts,
                "upstream_read_timeout_s": cfg.upstream_read_timeout_s,
                "fleet_scrape_s": (
                    balancer.fleet.interval_s if balancer.fleet else None
                ),
                "fleet_stale_after_s": (
                    balancer.fleet.stale_after_s if balancer.fleet else None
                ),
                "router": (
                    None if balancer.router is None
                    else balancer.router.cfg.policy
                ),
                "autoscaler": (
                    None if balancer.autoscaler is None
                    else balancer.autoscaler.config.snapshot()
                ),
            },
            "backends": fleet_mod.fetch_backend_configs(balancer),
        }
        _plain_response(client, 200, "OK", json.dumps(payload))
        return
    if route == "/metrics" and method == "GET":
        _plain_response(
            client, 200, "OK", render_gateway_metrics(balancer),
            ctype=PROM_CONTENT_TYPE,
        )
        return
    if route == "/debug/trace" and method == "GET":
        tid = parse_query(query).get("id", "")
        events = TRACER.for_trace(tid) if tid else []
        if not events:
            _plain_response(
                client, 404, "Not Found",
                '{"error":"unknown or expired trace id"}',
            )
            return
        _plain_response(client, 200, "OK", json.dumps(trace_payload(tid, events)))
        return
    if route == "/debug/flightrecord" and method == "GET":
        rec = last_flight_record()
        if rec is None:
            _plain_response(
                client, 404, "Not Found", '{"error":"no flight record yet"}'
            )
            return
        _plain_response(client, 200, "OK", json.dumps(rec))
        return
    if route in ("/gateway/drain", "/gateway/undrain") and method == "POST":
        key = parse_query(query).get("backend", "")
        draining = route == "/gateway/drain"
        if balancer.set_draining(key, draining):
            _plain_response(
                client, 200, "OK",
                json.dumps({"backend": key, "draining": draining}),
            )
        else:
            _plain_response(
                client, 404, "Not Found",
                json.dumps({"error": f"unknown backend {key!r}"}),
            )
        return
    _plain_response(client, 404, "Not Found", '{"error":"not found"}')


def _response_poison_fp(chunk: bytes) -> str | None:
    """Best-effort ``X-DLT-Poison-Fp`` implication header off the FIRST
    response chunk (server/quarantine.py) — the quarantine's strike
    evidence for failures the replica survived well enough to report.
    None when absent or the chunk isn't a response head."""
    try:
        line = chunk[: chunk.index(b"\r\n")].split()
        if len(line) < 2 or not line[0].startswith(b"HTTP/"):
            return None
    except (ValueError, IndexError):
        return None
    head = chunk.split(b"\r\n\r\n", 1)[0]
    for hline in head.split(b"\r\n")[1:]:
        if hline.lower().startswith(b"x-dlt-poison-fp:"):
            return hline.split(b":", 1)[1].strip().decode("latin-1")
    return None


def _proxy_once(
    client, request, b: Backend, config
) -> tuple[bool, bool, bool, bool, str | None]:
    """Forward `request` to backend `b`, streaming the response to `client`.
    Returns (failed, forwarded_any, client_gone, sent, poison_fp):
    `failed` = the UPSTREAM leg errored; `forwarded_any` = at least one
    response byte reached the client (the zero-byte-retry eligibility
    bit); `client_gone` = the CLIENT socket died (not the backend's fault
    — never counts against it); `sent` = the request bytes actually
    reached the backend (a connect-level refusal/timeout has `sent`
    False: the request was never in flight, so a failure there must not
    poison-strike it); `poison_fp` = the replica's implication header off
    the response head (quarantine strike evidence; None when absent)."""
    forwarded = False
    sent = False
    poison_fp = None
    try:
        with socket.create_connection(
            (b.host, b.port), timeout=config.connect_timeout_s
        ) as upstream:
            upstream.sendall(request)
            sent = True
            upstream.settimeout(config.upstream_read_timeout_s)
            while True:
                chunk = upstream.recv(16384)
                if not chunk:
                    # EOF before ANY response byte is a failure too (backend
                    # accepted, then FIN-closed mid-shutdown): an HTTP
                    # response is never legitimately empty, and treating it
                    # as success would hand the client an empty reply
                    # instead of the zero-byte retry
                    return not forwarded, forwarded, False, sent, poison_fp
                if not forwarded:
                    poison_fp = _response_poison_fp(chunk)
                try:
                    client.sendall(chunk)
                except OSError:
                    return False, forwarded, True, sent, poison_fp
                forwarded = True
    except OSError:
        return True, forwarded, False, sent, poison_fp


def handle_client(client: socket.socket, balancer: Balancer):
    config = balancer.config
    held = -1  # acquired-but-unreleased backend (crash safety net)
    tr = None
    t_req0 = 0
    path = ""
    outcome = "client_gone"  # overwritten on every terminal path below
    try:
        request = _read_http_request(client)
        if not request:
            return
        method, path = _request_line(request)
        route = path.partition("?")[0]
        # control routes the gateway answers ITSELF: its own stats/metrics
        # (incl. the federated fleet rollup), the trace/flightrecord views
        # of its own ring, the fleet signal table, and /debug/config (own
        # config + per-backend proxy). Every OTHER /debug/* route
        # (/debug/costs, /debug/profile, /debug/batch_timeline — the
        # engine-side endpoints) is backend state and proxies through like
        # a normal request.
        if route.startswith("/gateway/") or route == "/metrics" or route in (
            "/debug/trace", "/debug/flightrecord", "/debug/config"
        ):
            _handle_control(client, balancer, method, path, request)
            return
        # request-lifecycle trace: adopt the client's X-DLT-Trace-Id or
        # mint one; the SAME id rides every retried attempt (injected into
        # the forwarded bytes), so one trace stitches gateway -> retry ->
        # backend. The backend echoes the header to the client through the
        # transparent stream.
        tr = TRACER.start(
            _header_value(request, b"x-dlt-trace-id"),
            sampled=parse_sampled(_header_value(request, b"x-dlt-trace-sampled")),
        )
        request = _with_trace_header(request, tr.id, tr.sampled)
        hdrs = {TRACE_HEADER: tr.id}
        t_req0 = now_us()
        balancer.count("requests")
        # cache-aware routing (server/router.py): rank the backends by
        # prefix affinity × fleet signals ONCE per request — the plan rides
        # every retry attempt (the failed backend is excluded, the ranking
        # still stands). None = the router abstained (non-chat route,
        # unparsable body) or routing is off; selection is then pure
        # least-inflight, exactly the legacy behavior.
        plan = None
        router = balancer.router
        is_chat = method == "POST" and route == "/v1/chat/completions"
        # `routed` gates decision accounting to CHAT traffic: health/debug
        # proxies are not routing decisions, and counting them would dilute
        # the per-reason breakdown dashboards read
        routed = router is not None and is_chat
        body = request.partition(b"\r\n\r\n")[2] if is_chat else b""
        # poison-request quarantine + end-to-end deadline + routing plan:
        # all three identities come off ONE json.loads per request — and
        # with none of the three enabled, no parse at all (the proxy hot
        # path must not decode multi-megabyte bodies for nobody)
        fp = None
        deadline_mono = None
        text = None
        parsed = None
        dl_client = (
            _header_value(request, b"x-dlt-deadline-ms") if is_chat else None
        )
        deadline_possible = is_chat and (
            dl_client is not None
            or any(os.environ.get(v) for v in DEADLINE_ENVS)
        )
        if routed or deadline_possible or (
            is_chat and balancer.quarantine is not None
        ):
            try:
                parsed = json.loads(body)
            except ValueError:
                parsed = None
            messages = (
                parsed.get("messages") if isinstance(parsed, dict) else None
            )
            text = (
                messages_prefix_text(messages) if messages is not None
                else None
            )
        if routed:
            plan = router.plan(body, balancer, text=text)
        if is_chat and balancer.quarantine is not None:
            fp = request_fingerprint(text)
            if balancer.quarantine.is_quarantined(fp):
                # a fingerprint that already took down `limit` replicas is
                # refused terminally: 422 is a CLIENT error — the request
                # is the problem, and no amount of retrying will make
                # these bytes serve
                outcome = "quarantined_422"
                _respond_quarantined(client, balancer, fp, hdrs)
                return
        if deadline_possible:
            klass = _header_value(request, b"x-dlt-slo-class")
            if klass is None and isinstance(parsed, dict):
                raw = parsed.get("slo_class")
                klass = raw if isinstance(raw, str) else None
            ms = resolve_deadline_ms(klass, dl_client)
            if ms > 0:
                deadline_mono = time.monotonic() + ms / 1e3
        tried: set[int] = set()
        attempt = 0
        while True:
            if deadline_mono is not None and time.monotonic() >= deadline_mono:
                # the budget died in-house (queue wait, failed attempts):
                # 504 without burning a replica on an answer nobody is
                # still waiting for — `deadline` waste upstream never
                # becomes prefill waste downstream
                balancer.count("deadline_504")
                outcome = "504"
                _plain_response(
                    client, 504, "Gateway Timeout",
                    '{"error":"deadline exceeded"}', headers=hdrs,
                )
                return
            t_acq = time.perf_counter()
            idx = balancer.acquire(
                exclude=tried, prefer=plan.ranked if plan is not None else None
            )
            acq_us = int((time.perf_counter() - t_acq) * 1e6)
            held = idx if idx >= 0 else -1
            if idx < 0 and tried:
                # this request already failed zero-byte on some backend and
                # no alternative can take it (every other backend excluded,
                # open, or full): the original failure is the honest signal
                # — 502, not a shed/busy code that would misattribute it
                balancer.count("bad_gateway_502")
                outcome = "502"
                _plain_response(
                    client, 502, "Bad Gateway", '{"error":"backend failure"}',
                    headers=hdrs,
                )
                return
            if idx == Balancer.SHED:
                balancer.count("shed_503")
                outcome = "503"
                retry_after = max(1, math.ceil(balancer.retry_after_hint_s()))
                _plain_response(
                    client, 503, "Service Unavailable",
                    '{"error":"no healthy backend"}',
                    headers={"Retry-After": str(retry_after), **hdrs},
                )
                return
            if idx < 0:
                balancer.count("rejected_429")
                outcome = "429"
                _plain_response(
                    client, 429, "Too Many Requests",
                    '{"error":"all backends busy"}',
                    headers=hdrs,
                )
                return
            b = config.backends[idx]
            attempt += 1
            # once per ATTEMPT (bounded by retry_attempts, not tokens):
            # sanctioned cold emits inside the bounded retry loop
            tr.event(  # dlt: allow(trace-hot-emit)
                "gw_acquire", to_us(t_acq), acq_us,
                ("backend", "attempt"), (b.key, attempt),
            )
            if routed:
                # attribute + count the decision and land the scored
                # candidates on the trace — one event per attempt, same
                # bound as gw_acquire (locality learning waits for the
                # attempt to SUCCEED below)
                reason = router.resolve(plan, b.key)
                tr.event(  # dlt: allow(trace-hot-emit)
                    "gw_route", now_us(), 0,
                    ("backend", "reason", "candidates"),
                    (
                        b.key, reason,
                        "" if plan is None else " ".join(
                            f"{k}={s}" for k, s in plan.scored
                        ),
                    ),
                )
            request_out = request
            if plan is not None and plan.chain:
                # router prefetch hint (runtime/kv_tiering.py): name the
                # plan's chain keys so the backend's tiered KV store can
                # lift the matching prefix disk/peer -> host while the
                # prompt is still parsing. Re-stamped per attempt — a
                # retry's new backend deserves the hint as much as the
                # first choice did. Advisory: a backend without tiering
                # ignores it.
                request_out = _with_header(
                    request_out, PREFETCH_CHAIN_HEADER,
                    chain_header_value(plan.chain),
                )
            if deadline_mono is not None:
                # re-stamp the deadline with the REMAINING budget: one
                # clock rides routing and every retry, without shipping an
                # absolute timestamp between unsynchronized hosts
                remaining_ms = int((deadline_mono - time.monotonic()) * 1e3)
                request_out = _with_header(
                    request_out, DEADLINE_HEADER, str(max(remaining_ms, 1))
                )
            t_att = time.perf_counter()
            failed, forwarded, client_gone, sent, poison_fp = _proxy_once(
                client, request_out, b, config
            )
            tr.event(  # dlt: allow(trace-hot-emit)
                "gw_attempt", to_us(t_att),
                int((time.perf_counter() - t_att) * 1e6),
                ("backend", "attempt", "failed", "forwarded"),
                (b.key, attempt, int(failed), int(forwarded)),
                always=failed,  # failed attempts land even when unsampled
            )
            # snapshot the discount BEFORE release() records this very
            # failure: release(mark_unhealthy=True) can be the increment
            # that flips the breaker OPEN, and a backend that was
            # assignable when the attempt was made must not discount its
            # own death's strike (drains/opens that landed mid-flight
            # from OTHER causes are still visible here)
            discount = (
                _strike_discount_reason(balancer, idx)
                if fp is not None and failed and sent and poison_fp is None
                else None
            )
            balancer.release(idx, mark_unhealthy=failed)
            held = -1
            if fp is not None and (
                (failed and sent) or poison_fp is not None
            ):
                # strike the fingerprint: a transport-level death with the
                # request IN FLIGHT (zero-byte / midstream after sendall)
                # implicates the bytes the replica was holding; a survived
                # 5xx implicates only when the replica SAYS so
                # (X-DLT-Poison-Fp). A connect-level refusal/timeout never
                # strikes — the request never reached a replica, and two
                # briefly-down backends must not terminally 422 an
                # innocent conversation. Nor does a plain 503: landing on
                # an overloaded replica is not the request's fault. And a
                # transport death on a backend the fleet ALREADY marked
                # unhealthy (breaker open, stale scrape, draining) is
                # discounted — a rolling drain's correlated deaths
                # implicate the backend, not the request; a replica
                # NAMING the fp (poison_fp) is first-hand evidence and
                # always strikes.
                if discount is None:
                    balancer.quarantine.strike(fp)
                    balancer.count("poison_strikes")
                    if balancer.peering is not None:
                        # fleet-wide strike budget: peers learn this
                        # implication on the next gossip tick
                        balancer.peering.note_strike(fp)
                else:
                    balancer.count("poison_strikes_discounted")
                    tr.event(  # dlt: allow(trace-hot-emit)
                        "gw_strike_discounted", now_us(), 0,
                        ("backend", "reason"), (b.key, discount),
                        always=True,
                    )
            if client_gone:
                outcome = "client_gone"
                return
            if not failed:
                balancer.count("proxied_ok")
                outcome = "ok"
                if routed:
                    # the attempt SUCCEEDED: this backend is now the
                    # prefix's learned home (a zero-byte-failed attempt
                    # must never teach the locality map a dead backend)
                    router.learn(plan, b.key)
                    if plan is not None and balancer.peering is not None:
                        # peers learn the same affinity on the next
                        # gossip tick (LWW-versioned, server/peering.py)
                        balancer.peering.note_locality(plan.chain, b.key)
                return
            if forwarded:
                # mid-stream failure: appending a second status line to a
                # partially streamed response would corrupt the client's
                # stream; EOF is the only honest signal left — no retry
                balancer.count("midstream_failures")
                outcome = "midstream_eof"
                return
            if fp is not None and balancer.quarantine.is_quarantined(fp):
                # the quarantine just engaged mid-retry: STOP. Replaying
                # these bytes into yet another replica is exactly how one
                # poison request takes down a fleet — the strike ledger
                # caps the blast radius at `limit` replicas, terminally.
                outcome = "quarantined_422"
                _respond_quarantined(client, balancer, fp, hdrs)
                return
            # zero bytes reached the client: transparently retry on a
            # DIFFERENT backend (bounded; the failed one is excluded)
            tried.add(idx)
            if len(tried) > config.retry_attempts:
                balancer.count("bad_gateway_502")
                outcome = "502"
                _plain_response(
                    client, 502, "Bad Gateway", '{"error":"backend failure"}',
                    headers=hdrs,
                )
                return
            with balancer.lock:
                b.n_retries_away += 1
            balancer.count("zero_byte_retries")
            # once per retry decision: sanctioned cold emit
            tr.event(  # dlt: allow(trace-hot-emit)
                "gw_retry", now_us(), 0,
                ("attempt", "from_backend"), (attempt, b.key),
                always=True,
            )
    finally:
        if held >= 0:
            # an unexpected exception escaped between acquire and release:
            # give the slot back (a leak here would silently and permanently
            # remove the backend from rotation once it eats the inflight cap)
            balancer.release(held, mark_unhealthy=False)
        if tr is not None:
            dur_us = now_us() - t_req0
            # terminal span: non-ok outcomes land even when unsampled
            tr.event(
                "gw_request", t_req0, dur_us, ("path", "outcome"),
                (path, outcome), always=outcome not in ("ok", "client_gone"),
            )
            balancer.request_ms.observe(dur_us / 1e3)
        try:
            client.close()
        except OSError:
            pass


def serve(port: int, balancer: Balancer) -> socket.socket:
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("0.0.0.0", port))
    srv.listen(64)
    return srv


class GatewayServer:
    """The gateway's crash-only lifecycle: ONE object owns the listening
    socket and every background thread (fleet scraper, autoscaler, health
    prober, peer sync), so a restart is build-new-instance, not
    hunt-down-orphans. ``start()`` binds the port FIRST (failover clients
    connecting mid-restart queue in the listen backlog instead of being
    refused), runs the warm-restart recovery sweep (server/recovery.py),
    then starts the threads and the accept loop; ``shutdown()`` /
    ``server_close()`` stop EVERYTHING they started — the http.server
    naming contract, so harnesses tear a gateway down exactly like a
    replica server, and in-process restart tests can instantiate the
    gateway twice without the first instance's threads scraping on."""

    def __init__(self, port: int, balancer: Balancer):
        self.port = port
        self.balancer = balancer
        self._stop = threading.Event()
        self._srv: socket.socket | None = None
        self._accept_thread: threading.Thread | None = None
        self._scraper = None
        self._autoscaler = None
        self._peering = None
        self._prober = None
        self._closed = False
        # live client connections, for kill(): handler threads are
        # daemonic and outlive server_close(), so a crash-shaped teardown
        # must sever their sockets explicitly
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def start(self) -> "GatewayServer":
        from .autoscaler import Autoscaler
        from .fleet import FleetScraper
        from .peering import GatewayPeering
        from .recovery import recover_gateway
        from .router import Router

        bal = self.balancer
        cfg = bal.config
        # cache-aware routing (server/router.py): ON by default (DLT_ROUTER
        # / --router least_inflight keeps the legacy selection); None means
        # every routing call is skipped, not a null-check on the hot path
        if bal.router is None:
            bal.router = Router.build(cfg.router_policy)
        # bind BEFORE recovery: clients failing over to this address while
        # recovery runs queue in the listen backlog for its (bounded) wall
        # instead of getting connection-refused
        self._srv = serve(self.port, bal)
        self._srv.settimeout(0.5)
        # fleet signal plane: ATTACHED before recovery (the synchronous
        # scrape prime needs it), thread started after. A harness that
        # pre-attached its own scraper keeps it (manual-drive tests).
        if bal.fleet is None:
            scraper = FleetScraper(
                bal, interval_s=cfg.fleet_scrape_s,
                timeout_s=cfg.fleet_timeout_s,
            )
            if scraper.interval_s > 0:
                self._scraper = scraper
                bal.fleet = scraper
        # goodput-driven autoscaler: OFF unless asked (--autoscale-s /
        # DLT_AUTOSCALE_S > 0) — capacity decisions must be opt-in
        if bal.autoscaler is None:
            autoscaler = Autoscaler(bal, interval_s=cfg.autoscale_s)
            if autoscaler.interval_s > 0:
                self._autoscaler = autoscaler
                bal.autoscaler = autoscaler
        # active-active peering (server/peering.py): attached whenever
        # peers are configured (the receive path must answer even when the
        # push thread is disabled for manual-tick tests)
        if bal.peering is None and cfg.peer_gateways:
            self_id = cfg.gateway_id or f"{socket.gethostname()}:{self.port}"
            self._peering = GatewayPeering(
                bal, self_id=self_id, peers=list(cfg.peer_gateways),
                interval_s=cfg.peer_sync_s,
            )
            bal.peering = self._peering
        # crash-only warm restart (server/recovery.py): rebuild the
        # control-plane state from the fleet BEFORE taking traffic.
        # Default: recover whenever this gateway is fleet-aware (a scraper
        # is attached — it reads the same surfaces recovery does); a
        # fleet-blind gateway (every scraping-off test harness) starts
        # cold exactly as before. DLT_GW_RECOVER=0/1 overrides either way.
        recover = cfg.recover_on_start
        if recover is None:
            env = os.environ.get("DLT_GW_RECOVER")
            recover = (
                env not in ("0", "") if env is not None
                else bal.fleet is not None
            )
        if recover:
            bal.recovery = recover_gateway(bal)
        # threads start only now: a scraper racing the recovery sweep
        # would double-prime rate baselines mid-merge
        if self._scraper is not None:
            self._scraper.start()
        if self._autoscaler is not None:
            self._autoscaler.start()
        if self._peering is not None and self._peering.interval_s > 0:
            self._peering.start()
        if cfg.probe_interval_s > 0:
            self._prober = HealthProber(bal, self._stop)
            self._prober.start()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="gateway-accept"
        )
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                client, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # socket closed under us (server_close)
            with self._conns_lock:
                self._conns.add(client)
            threading.Thread(
                target=self._handle_tracked, args=(client,),
                daemon=True,
            ).start()

    def _handle_tracked(self, client: socket.socket):
        try:
            handle_client(client, self.balancer)
        except OSError:
            pass  # dlt: allow(swallowed-exception) — the connection was
            # severed under the handler (client reset, or kill() aborting
            # in-flight streams); there is no socket left to answer on
        finally:
            with self._conns_lock:
                self._conns.discard(client)

    def shutdown(self):
        """Stop accepting AND stop every gateway-owned thread — the
        restart tests instantiate a second gateway in-process, and a
        leaked scraper/autoscaler/peer-sync thread from the first would
        keep actuating against the same fleet (the sentinel-release leak
        class, thread edition — scripts/dlt_lint.py `thread-release`)."""
        self._stop.set()
        if self._peering is not None:
            self._peering.stop()
        if self._autoscaler is not None:
            self._autoscaler.stop()
        if self._scraper is not None:
            self._scraper.stop()
        # the prober shares self._stop; join it so no probe lands after
        # shutdown() returns
        if self._prober is not None:
            self._prober.join(timeout=5)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def server_close(self):
        if self._closed:
            return
        self._closed = True
        self.shutdown()
        if self._srv is not None:
            self._srv.close()

    # operator ergonomics: one call tears everything down
    close = server_close

    def kill(self):
        """Crash-shaped teardown (chaos harnesses): ``server_close()``
        PLUS a hard abort of every in-flight proxied connection. A real
        gateway crash severs mid-stream bytes; the graceful close alone
        lets the daemonic handler threads finish their streams, which is
        a strictly softer fault than the one warm-restart recovery
        exists for."""
        self.server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass


def run(port: int, balancer: Balancer, stop_event: threading.Event | None = None):
    """Blocking entry point (the CLI + test harnesses): builds a
    :class:`GatewayServer`, serves until ``stop_event`` is set, and tears
    every gateway-owned thread down on the way out."""
    server = GatewayServer(port, balancer).start()
    print(f"⚖️ Gateway listening on {port} -> {len(balancer.config.backends)} backends")
    stop = stop_event if stop_event is not None else threading.Event()
    try:
        while not stop.wait(0.2):
            pass
    finally:
        server.server_close()


def parse_backend(s: str) -> Backend:
    host, port = s.rsplit(":", 1)
    return Backend(host, int(port))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="dllama-gateway")
    p.add_argument("--port", type=int, default=9999)
    p.add_argument("--backend", action="append", required=True, help="host:port (repeatable)")
    p.add_argument("--max-inflight-per-backend", type=int, default=4)
    p.add_argument("--queue-size", type=int, default=16)
    p.add_argument("--queue-timeout-s", type=float, default=30.0)
    p.add_argument("--breaker-threshold", type=int, default=3,
                   help="consecutive failures before the breaker opens")
    p.add_argument("--breaker-backoff-s", type=float, default=1.0)
    p.add_argument("--breaker-backoff-max-s", type=float, default=30.0)
    p.add_argument("--probe-interval-s", type=float, default=2.0,
                   help="active /health probe interval; <=0 disables")
    p.add_argument("--retry-attempts", type=int, default=2,
                   help="additional backends tried after a zero-byte failure")
    p.add_argument("--upstream-timeout-s", type=float, default=600.0)
    p.add_argument("--health-retry-ms", type=int, default=None,
                   help="legacy: seeds the breaker's initial backoff")
    p.add_argument("--fleet-scrape-s", type=float, default=None,
                   help="per-replica /metrics+/stats scrape interval for "
                   "/gateway/fleet and the federated /metrics rollup "
                   "(default: DLT_FLEET_SCRAPE_S or 2.0; <=0 disables)")
    p.add_argument("--fleet-timeout-s", type=float, default=None,
                   help="per-scrape socket timeout (default: "
                   "DLT_FLEET_TIMEOUT_S or 2.0)")
    p.add_argument("--router", choices=["cache_aware", "least_inflight"],
                   default=None,
                   help="backend selection policy (server/router.py): "
                   "cache_aware lands shared-prefix traffic on the replica "
                   "whose radix cache holds it, scored against the fleet "
                   "signal table; least_inflight keeps the legacy "
                   "selection (default: DLT_ROUTER or cache_aware)")
    p.add_argument("--autoscale-s", type=float, default=None,
                   help="goodput-driven autoscaler tick interval "
                   "(server/autoscaler.py): drains idle replicas with warm "
                   "prefix handoff, undrains on pressure (default: "
                   "DLT_AUTOSCALE_S or 0 = off)")
    p.add_argument("--quarantine-strikes", type=int, default=None,
                   help="poison-request quarantine strike limit "
                   "(server/quarantine.py): failed attempts implicating "
                   "the same request fingerprint stop being retried and "
                   "422 terminally past this count (default: "
                   "DLT_QUARANTINE_STRIKES or 2; <=0 disables)")
    p.add_argument("--peer-gateway", action="append", default=None,
                   help="host:port of ANOTHER gateway serving this fleet "
                   "(repeatable; configure a full mesh). Peered gateways "
                   "gossip locality learns, quarantine strikes, and "
                   "drain events (server/peering.py) and elect one "
                   "autoscaler leader (lowest live id)")
    p.add_argument("--peer-sync-s", type=float, default=None,
                   help="peer gossip tick interval (default: "
                   "DLT_GW_PEER_SYNC_S or 2.0)")
    p.add_argument("--gateway-id", default=None,
                   help="this gateway's identity for peering LWW origins "
                   "and leader election (default: <hostname>:<port>)")
    p.add_argument("--no-recover", action="store_true",
                   help="skip the warm-restart recovery sweep "
                   "(server/recovery.py): start with a cold control "
                   "plane instead of rebuilding locality/quarantine/"
                   "drain state from the fleet")
    args = p.parse_args(argv)
    config = GatewayConfig(
        backends=[parse_backend(b) for b in args.backend],
        max_inflight_per_backend=args.max_inflight_per_backend,
        queue_size=args.queue_size,
        queue_timeout_s=args.queue_timeout_s,
        breaker_failure_threshold=args.breaker_threshold,
        breaker_backoff_s=args.breaker_backoff_s,
        breaker_backoff_max_s=args.breaker_backoff_max_s,
        probe_interval_s=args.probe_interval_s,
        retry_attempts=args.retry_attempts,
        upstream_read_timeout_s=args.upstream_timeout_s,
        health_retry_ms=args.health_retry_ms,
        fleet_scrape_s=args.fleet_scrape_s,
        fleet_timeout_s=args.fleet_timeout_s,
        router_policy=args.router,
        autoscale_s=args.autoscale_s,
        quarantine_strikes=args.quarantine_strikes,
        peer_gateways=args.peer_gateway,
        peer_sync_s=args.peer_sync_s,
        gateway_id=args.gateway_id,
        recover_on_start=False if args.no_recover else None,
    )
    run(args.port, Balancer(config))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
