"""OpenAI-compatible HTTP API server.

Wire-compatible with the reference server (reference: src/dllama-api.cpp):

* ``POST /v1/chat/completions`` — stream (SSE ``data: {chunk}\\r\\n\\r\\n``
  terminated by ``data: [DONE]``) and non-stream; params `messages`,
  `temperature`, `top_p`, `seed`, `max_tokens`, `stream`
  (reference: parseRequest, dllama-api.cpp:501-530);
* ``GET /v1/models`` — single-model list;
* **radix prefix cache** (runtime/prefix_cache.py): every request
  longest-prefix-matches a trie of published KV slices, so successive chat
  turns — and UNRELATED requests sharing a system prompt — resume from
  cached KV instead of re-prefilling. Unlike the retired ``NaiveCache``
  (one remembered conversation, thrashed by two interleaved users), the
  radix cache is multi-conversation and applies on BOTH the serialized and
  the batched (Batcher) paths. On by default (``--prefix-cache-mb``,
  ``DLT_PREFIX_CACHE_MB``; 0 disables); observable via ``/stats``
  (``prefix_hits``/``prefix_hit_tokens``/``prefix_cache_bytes``/
  ``prefix_evictions`` and the ``prefix_cache`` section).

batch == 1 serves sequentially (one engine, one KV cache) exactly like the
reference's accept loop; horizontal scale comes from the gateway
(server/gateway.py) across replicas.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np

from ..runtime.engine import InferenceEngine
from ..runtime.grammar import GrammarError
from ..runtime.telemetry import (
    GoodputAggregator,
    GoodputLedger,
    LEDGER_TRACE_KEYS,
)
from ..runtime.tracing import (
    BATCH_TIMELINE_NAMES,
    PROM_CONTENT_TYPE,
    SAMPLED_HEADER,
    TRACE_HEADER,
    TRACER,
    batch_timeline_payload,
    flight_record,
    last_flight_record,
    now_us,
    parse_sampled,
    render_step_stats,
    to_us,
    trace_payload,
)
from . import parse_query
from .quarantine import (
    POISON_HEADER,
    QuarantineLedger,
    fp_hex,
    request_fingerprint,
)
from .scheduler import (
    DEADLINE_HEADER,
    DEFAULT_CLASS,
    HotPrefixTracker,
    SLO_CLASS_HEADER,
    SloScheduler,
    resolve_deadline_ms,
    resolve_slo_class,
)
from ..tokenizer import (
    ChatItem,
    ChatTemplateGenerator,
    EOS_FOUND,
    EOS_MAYBE,
    EosDetector,
    Sampler,
    TEMPLATE_UNKNOWN,
    Tokenizer,
)

MODEL_NAME = "Distributed Model"


class PromptTooLong(ValueError):
    pass


class Overloaded(Exception):
    """The serving queue is past its shed threshold: fail fast with
    503 + Retry-After instead of letting the request sit in a backlog it
    will very likely time out of anyway (load shedding under pressure)."""

    def __init__(self, retry_after_s: int = 1):
        super().__init__("server overloaded")
        self.retry_after_s = retry_after_s


class ClientDisconnected(Exception):
    """The HTTP client dropped mid-stream (raised from the emit path). The
    engine state is fine — distinguished by TYPE from engine failures so
    recovery logic can't confuse the two (an engine error travelling as a
    ConnectionError through the device tunnel must still trigger recovery)."""


class DeadlineExceeded(Exception):
    """The request's end-to-end deadline (``X-DLT-Deadline-Ms``, minted at
    the gateway — server/scheduler.py ``resolve_deadline_ms``) passed
    before delivery. Mapped to ``504``; the goodput ledger labels every
    token it burned ``deadline`` — an answer nobody was still waiting
    for is pure waste, however correct."""


@dataclass
class CacheItem:
    end_pos: int
    role: str
    content: str


class NaiveCache:
    """DEPRECATED: KV-prefix reuse across chat turns (reference:
    dllama-api.cpp:296-341). Retired in favor of the engine's radix prefix
    cache (runtime/prefix_cache.py), which is multi-conversation correct —
    NaiveCache remembered exactly ONE conversation, so two interleaved
    users evicted each other's prefix on every turn (the "interleaved-user
    thrash"). The class is kept for API compatibility and as the reference
    baseline; the server no longer constructs it. The old per-request miss
    signal survives as the ``cache_miss`` StepStats counter (a chat request
    that reused zero prefix tokens)."""

    def __init__(self):
        self.items: list[CacheItem] = []

    def clear(self):
        self.items = []

    def push(self, end_pos: int, role: str, content: str):
        self.items.append(CacheItem(end_pos, role, content))

    def resolve_delta_prompt(self, messages: list[dict]) -> tuple[list[dict], int]:
        """Returns (delta messages to prefill, start position)."""
        n = len(self.items)
        if n == 0:
            return messages, 0
        if len(messages) > n:
            i = 0
            while i < n:
                if (
                    self.items[i].role != messages[i]["role"]
                    or self.items[i].content != messages[i]["content"]
                ):
                    break
                i += 1
            if i == n:
                start = self.items[i - 1].end_pos
                return messages[i:], start
        self.cache_miss()
        return messages, 0

    def cache_miss(self):
        self.items = []


def chunk_json(delta: str | None, stop: bool) -> dict:
    choice = {"index": 0, "finish_reason": "stop" if stop else ""}
    if not stop:
        choice["delta"] = {"role": "assistant", "content": delta or ""}
    return {
        "id": "cmpl-c0",
        "object": "chat.completion",
        "created": 0,
        "model": MODEL_NAME,
        "choices": [choice],
    }


class _BatchReq:
    """One request's slot in a batched generation round.

    Tokens flow from the batch thread to the client through `emit`, a
    bounded queue drained by the REQUEST's own handler thread (Batcher
    .submit): the step loop never runs client I/O, so one slow client's
    socket cannot stall co-batched streams (the reference's serial accept
    loop stalls everyone, dllama-api.cpp:571-576). A client that falls
    more than EMIT_DEPTH tokens behind is dropped — that row alone."""

    EMIT_DEPTH = 8192

    def __init__(self, ids, max_new, temperature, topp, seed, on_token,
                 eos_ids=frozenset(), trace=None, slo_class=DEFAULT_CLASS,
                 deadline=None, grammar=None):
        import queue

        self.ids = ids
        self.max_new = max_new
        self.temperature = temperature
        self.topp = topp
        self.seed = seed
        self.on_token = on_token  # on_token(tok) -> None; may set .stopped
        # end-to-end deadline as a monotonic instant (None = none): the
        # Batcher sheds this request from the backlog before spending
        # prefill on it, and retires it at the first decode-chunk boundary
        # past the deadline — tokens past it are `deadline` waste
        self.deadline = deadline
        # SLO class (server/scheduler.py): admission priority, shed/preempt
        # eligibility, and the per-class goodput label
        self.slo_class = resolve_slo_class(slo_class)
        self.preempted = False  # set by the loop's preemption decision so
        # the retirement ledger can label the waste "preempt", not "shed"
        # per-request goodput ledger (runtime/telemetry.py): the Batcher
        # loop accumulates walls/tokens into it; complete_batched finalizes
        # and folds it into the process aggregate at retirement
        self.ledger = GoodputLedger(
            prompt_tokens=len(ids), slo_class=self.slo_class
        )
        # request-lifecycle tracing (runtime/tracing.py): the Batcher loop
        # emits this request's queue-wait/decode/spec spans through the
        # pre-bound emitters (one tuple append per chunk; None = untraced
        # or unsampled, and every emission site guards on it)
        self.trace = trace
        self.t_enqueue_us = 0  # set by submit(); queue_wait span base
        self._em_decode = trace.bind("decode_chunk", ("n",)) if trace else None
        self._em_spec = (
            trace.bind("spec_round", ("drafted", "accepted")) if trace else None
        )
        # token ids that END the row — checked IN the step loop, so a row
        # stops decoding at its EOS token instead of running up to a full
        # extra chunk before the writer thread's `stopped` flag is seen
        self.eos_ids = frozenset(eos_ids)
        # structured output (runtime/grammar.py): the request's compiled
        # grammar (None = unconstrained). The SESSION — the arena span +
        # per-row DFA state — is built by the Batcher loop at admission, ON
        # the engine thread: an arena install mutates the shared table the
        # next dispatch uploads, so handler threads must never touch it.
        self.grammar = grammar
        self.grammar_session = None  # set at admission; closed at _finish
        self.stopped = False
        self.kv_external = None  # deferred disaggregated-KV insert
        # (server/disagg.PendingExternalKv): the Batcher loop applies it on
        # the engine thread right before this request's admission
        self.prefilling = False  # admitted, prompt still prefilling in
        # bounded chunks between decode steps (interleaved admission)
        self.out_ids: list = []  # raw token ids delivered to the emit
        # queue, in order — the retirement-time prefix-cache publish needs
        # the row's actual token chain (ids + generated)
        self.n = 0  # tokens decoded into this row (budget accounting)
        self.n_out = 0  # tokens actually delivered to on_token (usage
        # accounting: excludes post-stop overrun the writer drains away)
        self.n_overrun = 0  # chunk-tail tokens the engine decoded PAST
        # this row's stop point (EOS / max_new / writer stop): real decode
        # compute that is never delivered and never enters req.n — counted
        # into the goodput ledger's discarded ("overrun") waste at
        # retirement so the burned chunk tail is visible, not vanished
        self.error = None
        self.done = threading.Event()
        self.emit: "queue.Queue[int | None]" = queue.Queue(maxsize=self.EMIT_DEPTH)


#: queue sentinel waking the Batcher loop for shutdown (never a request)
_BATCHER_STOP = object()


class Batcher:
    """Continuous batching: rolling admission into a BatchSession.

    The reference serializes requests entirely (one sequential accept loop,
    dllama-api.cpp:571-576); the gateway's replica DP is its only
    concurrency. Here a worker thread owns a `BatchSession`
    (runtime/batch_session.py) whose rows are independent parkable slots:

    * a request arriving at ANY time is admitted into a free slot at the
      next decode-chunk boundary (at most one chunk of latency, not a whole
      round) — its prompt prefills into its row without disturbing rows
      mid-generation;
    * rows finish independently: a short request's latency never depends on
      a long co-tenant's budget, and its freed slot is immediately
      re-admittable;
    * sampling settings are PER ROW (traced vectors): mixed
      temperature/top-p traffic — and explicitly seeded requests — co-batch
      freely. A seeded request's stream depends only on its seed and step
      count (per-row threefry chains), so it reproduces regardless of what
      it shares chunks with;
    * admissions ride the engine's radix PREFIX CACHE
      (runtime/prefix_cache.py): a staged prompt longest-prefix-matches the
      trie at `begin_admit`, splices the cached KV at its first prefill
      chunk, and every retired row publishes its conversation KV back —
      shared system prompts and multi-turn histories reuse device KV
      across co-batched users.
    """

    def __init__(self, state: "ApiState", chunk_size: int | None = None,
                 max_backlog: int | None = None,
                 prefill_budget: int | None = None):
        import queue

        self.state = state
        engine = state.engine
        # chunk = admission latency quantum. Smaller admits faster but pays
        # more dispatch round trips per token; the engine default balances
        # the two for throughput.
        self.chunk = chunk_size or engine.decode_chunk_size
        # interleaved admission: a newcomer's prompt prefills at most this
        # many tokens per decode-chunk boundary (one max_chunk prefill chunk
        # by default), bounding the latency bump co-batched decode streams
        # see while a long prompt lands. With NO live decode streams the
        # budget is ignored and the prompt prefills in one go (nothing to
        # starve, minimal TTFT).
        self.prefill_budget = prefill_budget or engine.max_chunk
        # shed threshold: with this many requests already waiting for a
        # slot, a newcomer is turned away with 503 + Retry-After instead of
        # joining a backlog it would likely rot in (see ApiState shedding)
        self.max_backlog = max_backlog if max_backlog is not None else 8 * engine.batch
        # SLO-class scheduling policy (server/scheduler.py): per-class
        # admission quotas, queue priorities, shed-victim/preemption
        # selection, and the (class, action) decision counters /metrics
        # exports as dlt_scheduler_decisions_total
        self.scheduler = SloScheduler()
        # per-class count of submissions still sitting in self.q (accepted
        # but not yet drained into the class backlog by the loop): the
        # quota check must see them, or a burst landing mid-chunk would
        # bypass its class's share entirely and shed-starve the others
        from .scheduler import SLO_CLASSES as _classes

        self._pending_by_class = {c: 0 for c in _classes}
        self._pending_lock = threading.Lock()
        self.q: "queue.Queue[_BatchReq]" = queue.Queue()
        # batch-composition timeline (runtime/tracing.py): one sampled
        # snapshot of slot state per step into the bounded TraceRing —
        # decoding/prefilling/free rows, spec round flag, KV-pool pages,
        # backlog depth — served post-hoc at /debug/batch_timeline.
        # DLT_BATCH_TIMELINE=0 disables; DLT_BATCH_TIMELINE_SAMPLE=N keeps
        # one step in N (default 1 = all; the ring bounds memory either
        # way). Emission is a pre-bound tuple append: zero device work.
        import os

        try:
            sample = int(os.environ.get("DLT_BATCH_TIMELINE_SAMPLE", "1"))
        except ValueError:
            sample = 1
        if os.environ.get("DLT_BATCH_TIMELINE", "1") in ("0", ""):
            sample = 0
        self.timeline_sample = max(sample, 0)
        self._em_timeline = (
            TRACER.bind_global(
                "batch_step",
                ("decoding", "prefilling", "free", "spec",
                 "pool_pages_used", "queue_depth"),
            )
            if self.timeline_sample > 0
            else None
        )
        self._timeline_n = 0
        # observable serving state (/stats): the loop owns the mutations,
        # readers take racy-but-consistent-enough snapshots
        self.slots: list[_BatchReq | None] = [None] * engine.batch
        self.backlog: "object" = None  # set by the loop (deque)
        self._stopping = False  # set by stop(); the loop exits at the next
        # boundary, failing whatever is still in flight — teardown must
        # release the engine (and its sealed sentinel), not strand it on a
        # daemon thread forever (the cross-suite sentinel-leak class)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 5.0):
        """Shut the step loop down: in-flight and queued requests fail with
        503-shaped errors, the loop thread exits, and the engine is no
        longer referenced by a live thread — so ``ApiState.close`` can
        actually release it (sentinel unsubscribed, fetch pool down)."""
        if self._stopping:
            self._thread.join(timeout=timeout)
            return
        self._stopping = True
        self.q.put(_BATCHER_STOP)  # wake the idle blocking get
        self._thread.join(timeout=timeout)

    def stats(self) -> dict:
        from .scheduler import SLO_CLASSES

        slots = list(self.slots)
        backlog = self.backlog
        return {
            "batch_slots": len(slots),
            "slots_active": sum(1 for s in slots if s is not None),
            "slots_prefilling": sum(
                1 for s in slots if s is not None and s.prefilling
            ),
            "queue_depth": self.queue_depth(),
            # per-class backlog occupancy (server/scheduler.py ClassQueues;
            # zeros before the loop's first iteration builds the queues)
            "queue_depths": (
                backlog.depths() if backlog is not None
                else {c: 0 for c in SLO_CLASSES}
            ),
            "max_backlog": self.max_backlog,
            "chunk_size": self.chunk,
            "prefill_budget": self.prefill_budget,
        }

    def queue_depth(self) -> int:
        return (len(self.backlog) if self.backlog is not None else 0) + self.q.qsize()

    def overloaded(self) -> bool:
        return self.queue_depth() >= self.max_backlog

    def admission_blocked(self, klass: str) -> bool:
        """Class-aware shed decision: the total-backlog cap (`overloaded`,
        kept as its own method — tests and operators override it) OR the
        class's own quota share of the backlog (server/scheduler.py) —
        a batch flood must shed against its quota while interactive
        admissions still sail through. Read-only; the serving path uses
        :meth:`try_reserve`, whose check-and-increment is ONE lock hold
        (a concurrent burst must not all pass the check before any member
        is counted)."""
        if self.overloaded():
            return True
        backlog = self.backlog
        if backlog is None:
            return False
        with self._pending_lock:
            pending = self._pending_by_class.get(
                resolve_slo_class(klass), 0
            )
        return not self.scheduler.admission_allowed(
            klass, backlog, self.max_backlog, extra_depth=pending
        )

    def try_reserve(self, klass: str) -> bool:
        """Atomically admit-or-shed one ``klass`` request: the quota check
        and the pending-count increment happen under ONE lock hold, so N
        concurrent submissions consume N quota slots — never all passing a
        stale zero first. The reservation is consumed when the loop drains
        the submitted request (``_drained``); a caller that fails before
        handing the request to :meth:`submit` must
        :meth:`release_reservation`."""
        klass = resolve_slo_class(klass)
        if self.overloaded():
            return False
        backlog = self.backlog
        with self._pending_lock:
            pending = self._pending_by_class.get(klass, 0)
            if backlog is not None and not self.scheduler.admission_allowed(
                klass, backlog, self.max_backlog, extra_depth=pending
            ):
                return False
            self._pending_by_class[klass] = pending + 1
        return True

    def release_reservation(self, klass: str):
        with self._pending_lock:
            n = self._pending_by_class.get(resolve_slo_class(klass), 0)
            self._pending_by_class[resolve_slo_class(klass)] = max(n - 1, 0)

    def submit(self, req: _BatchReq):
        """Enqueue and then act as the request's emit-queue writer: client
        I/O (on_token -> SSE socket writes) happens HERE, on the handler's
        thread, never on the batch step loop. An on_token failure (client
        gone, or just too slow to drain) marks the row stopped; the loop
        retires it at the next chunk boundary."""
        import queue

        req.t_enqueue_us = now_us()
        self.q.put(req)
        while True:
            try:
                t = req.emit.get(timeout=0.5)
            except queue.Empty:
                if req.done.is_set():
                    break
                continue
            if t is None:  # sentinel from _finish
                break
            if req.stopped:
                continue  # drain and discard after a failed write
            try:
                req.n_out += 1
                req.on_token(t)
            except Exception as e:
                req.error = req.error or e
                req.stopped = True
        # the row is retired; deliver any tokens still queued behind the
        # sentinel (generated in the final chunk before done was set)
        while not req.stopped:
            try:
                t = req.emit.get_nowait()
            except queue.Empty:
                break
            if t is None:
                continue
            try:
                req.n_out += 1
                req.on_token(t)
            except Exception as e:
                req.error = req.error or e
                req.stopped = True
        req.done.wait()
        if req.error is not None:
            raise req.error

    @staticmethod
    def _key_for_seed(seed: int):
        """[2] uint32 threefry state from a request seed, via the same
        xorshift* state derivation as the host Sampler (so a given seed
        names one stream everywhere)."""
        from ..runtime.engine import _sampler_prng_key
        from ..tokenizer import Sampler

        import jax

        s = Sampler(1, 1.0, 0.9, seed)
        return np.asarray(jax.random.key_data(_sampler_prng_key(s)))

    def _finish(self, req: _BatchReq, session, slots, row):
        import queue

        if req.trace is not None:
            # terminal event: errors land even for unsampled traces, so a
            # failed request is always reconstructable from /debug/trace
            req.trace.event(
                "finish", now_us(), 0, ("tokens", "error"),
                (req.n_out, 1 if req.error is not None else 0),
                always=req.error is not None,
            )
        if req.error is None and not req.prefilling and req.out_ids:
            # publish the retired row's conversation KV (prompt + generated)
            # into the prefix cache BEFORE parking it, so this user's next
            # turn — on any row — splices instead of re-prefilling. Best
            # effort: a publish failure must never fail the request.
            try:
                session.publish_row(row, list(req.ids) + req.out_ids)
            except Exception:
                self.state.engine.stats.incr("prefix_publish_failed")
        if req.grammar_session is not None:
            # release the arena span (zero-ref spans are LRU-evictable);
            # the compiled grammar itself stays in the ApiState LRU
            req.grammar_session.close()
            req.grammar_session = None
        session.release(row)
        slots[row] = None
        req.done.set()
        try:
            req.emit.put_nowait(None)  # wake the writer (FIFO: after tokens)
        except queue.Full:
            pass  # writer will notice done via its get timeout

    def _timeline_step(
        self, engine, slots, n_decoding: int, t_us: int, dur_us: int,
        spec: bool,
    ):
        """One sampled batch-composition snapshot: slot roles + pool/backlog
        occupancy at this step boundary. A pre-bound tuple append when it
        fires; a counter bump and a modulo when sampled out."""
        em = self._em_timeline
        if em is None:
            return
        self._timeline_n += 1
        if self._timeline_n % self.timeline_sample != 0:
            return
        n_prefilling = sum(
            1 for s in slots if s is not None and s.prefilling
        )
        n_free = sum(1 for s in slots if s is None)
        em(
            t_us, dur_us, n_decoding, n_prefilling, n_free,
            1 if spec else 0,
            engine.page_pool.used_pages if engine.paged else 0,
            self.queue_depth(),
        )

    def _shed_expired(self, session, slots):
        """Per-chunk-boundary deadline sweep: a row whose end-to-end
        deadline passed retires NOW — decode and PREFILL alike are
        compute for an answer the client stopped waiting for. Tokens it
        already decoded are labeled `deadline` waste at retirement
        (complete_batched's ledger path)."""
        now_mono = time.monotonic()
        engine = self.state.engine
        for row, req in enumerate(slots):
            if (
                req is None or req.deadline is None
                or now_mono <= req.deadline
            ):
                continue
            engine.stats.incr("deadline_expired")
            # timeline mark: once per expiry decision, cold path
            TRACER.event(  # dlt: allow(trace-hot-emit)
                "batch_shed", now_us(), 0,
                ("row", "reason", "slo_class"),
                (row, "deadline", req.slo_class),
            )
            req.error = req.error or DeadlineExceeded(
                "deadline passed mid-serve"
            )
            self._finish(req, session, slots, row)

    def _drained(self, req: _BatchReq):
        """One request moved from self.q into the class backlog: its
        quota accounting moves with it (the backlog's own depth counts it
        from here on)."""
        with self._pending_lock:
            n = self._pending_by_class.get(req.slo_class, 0)
            self._pending_by_class[req.slo_class] = max(n - 1, 0)

    def _loop(self):
        import queue

        from ..runtime.batch_session import BatchSession
        from ..runtime.paged_kv import PagePoolExhausted

        from .scheduler import ClassQueues

        engine = self.state.engine
        session = BatchSession(engine)
        slots = self.slots
        # class-priority backlog (server/scheduler.py): interactive drains
        # before standard drains before batch; within a class, FIFO — the
        # pre-SLO-class all-standard behavior is byte-identical
        backlog = ClassQueues()
        self.backlog = backlog
        ramped_last = False
        preempted_last = False  # one preemption per chunk boundary: reset
        # only after a decode chunk actually ran, so a backlog of waiters
        # cannot cascade-evict every lower-class row with zero decode
        # steps between (the twin's one-outstanding-preemption rule)

        while True:
            if self._stopping:
                # teardown: fail everything still queued or in flight so
                # writers unblock, then exit — the engine is now
                # releasable (ApiState.close owns the actual close)
                for row, req in enumerate(slots):
                    if req is not None:
                        req.error = req.error or Overloaded(retry_after_s=2)
                        self._finish(req, session, slots, row)
                for req in list(backlog):
                    req.error = Overloaded(retry_after_s=2)
                    req.done.set()
                while True:
                    try:
                        req = self.q.get_nowait()
                    except queue.Empty:
                        break
                    if req is _BATCHER_STOP:
                        continue
                    req.error = Overloaded(retry_after_s=2)
                    req.done.set()
                return
            # drain the queue into the class backlog; block only when fully
            # idle (no active slots and nothing waiting)
            idle = all(s is None for s in slots)
            if idle and not backlog:
                req = self.q.get()
                if req is _BATCHER_STOP:
                    continue
                self._drained(req)
                backlog.append(req, req.slo_class)
            while True:
                try:
                    req = self.q.get_nowait()
                except queue.Empty:
                    break
                if req is _BATCHER_STOP:
                    continue
                self._drained(req)
                backlog.append(req, req.slo_class)
            # admit in class-priority order into free slots at this chunk
            # boundary (within a class: arrival order).
            # Admission only STAGES the prompt (begin_admit): the prefill
            # itself advances in bounded chunks interleaved between decode
            # steps below, so a long newcomer prompt no longer stalls every
            # co-batched decode stream for its whole prefill (the old
            # admit-then-full-prefill behavior; Sarathi-style piggyback).
            for row in range(engine.batch):
                if slots[row] is not None or not backlog:
                    continue
                req = backlog.popleft()
                if req.deadline is not None and time.monotonic() > req.deadline:
                    # the deadline passed while the request sat in the
                    # backlog: shed it BEFORE spending a prefill on an
                    # answer nobody is waiting for — the cheapest token is
                    # the one never decoded
                    engine.stats.incr("deadline_shed")
                    self.scheduler.record(req.slo_class, "shed_backlog")
                    # timeline mark: once per shed decision, cold path
                    TRACER.event(  # dlt: allow(trace-hot-emit)
                        "batch_shed", now_us(), 0,
                        ("row", "reason", "slo_class"),
                        (row, "deadline", req.slo_class),
                    )
                    req.error = DeadlineExceeded(
                        "deadline passed before admission"
                    )
                    req.done.set()
                    continue
                try:
                    nowu = now_us()
                    t0 = req.t_enqueue_us or nowu
                    req.ledger.queue_us = max(nowu - t0, 0)
                    if req.trace is not None:
                        # once per REQUEST (not per token): sanctioned cold
                        # emit inside the admission sweep
                        req.trace.event(  # dlt: allow(trace-hot-emit)
                            "queue_wait", t0, max(nowu - t0, 0), ("row",), (row,)
                        )
                    if req.kv_external is not None:
                        # deferred disaggregated-KV insert: THIS thread owns
                        # the engine's dispatches, so the paged scatter (or
                        # contiguous device_put) is race-free here, and the
                        # begin_admit below then matches the fresh entry
                        req.kv_external.apply(self.state)
                        req.kv_external = None
                    key = self._key_for_seed(req.seed) if req.seed is not None else None
                    if req.grammar is not None:
                        # arena install on THIS thread (it mutates the
                        # shared table the next dispatch uploads); mixed
                        # constrained/unconstrained rows co-batch through
                        # the one warm program — free rows ride state 0
                        from ..runtime.grammar import GrammarSession

                        req.grammar_session = GrammarSession(
                            engine.grammar, req.grammar
                        )
                    session.begin_admit(
                        row, req.ids, temperature=req.temperature,
                        topp=req.topp, key_data=key, trace=req.trace,
                        grammar=req.grammar_session,
                    )
                    req.ledger.prefix_hit_tokens = session.pending_resume(row)
                    req.prefilling = True
                    slots[row] = req
                    self.scheduler.record(req.slo_class, "admit")
                except Exception as e:
                    if req.grammar_session is not None:
                        req.grammar_session.close()
                        req.grammar_session = None
                    req.error = e
                    req.done.set()

            # per-boundary deadline sweep over ALL active rows —
            # PREFILLING included: a request whose deadline passed must
            # stop burning prefill chunks exactly as it stops burning
            # decode chunks (the pre-admission shed above catches only
            # deadlines that died in the backlog; without this a long
            # prompt with a short deadline would keep prefilling for
            # dozens of boundaries after its answer went worthless)
            self._shed_expired(session, slots)

            # class preemption (server/scheduler.py): with every slot held
            # and a higher-class request waiting, evict the lowest-class
            # least-progress decoding row (strictly below the waiter's
            # class — standard never preempts standard) so the waiter is
            # admitted at the NEXT boundary instead of after a batch
            # co-tenant's whole budget. At most one preemption per chunk
            # boundary (`preempted_last` holds until a decode chunk runs);
            # the victim gets the standard 503 + Retry-After.
            if backlog and not preempted_last and all(
                s is not None for s in slots
            ):
                victim = self.scheduler.preempt_victim(
                    backlog.peek_class(),
                    [
                        (r, s.slo_class, s.n)
                        for r, s in enumerate(slots)
                        if s is not None and not s.prefilling
                    ],
                )
                if victim is not None:
                    preempted_last = True
                    vreq = slots[victim]
                    vreq.preempted = True
                    vreq.error = vreq.error or Overloaded(retry_after_s=1)
                    self.scheduler.record(vreq.slo_class, "preempt")
                    # timeline mark: once per preemption decision, cold path
                    TRACER.event(  # dlt: allow(trace-hot-emit)
                        "batch_shed", now_us(), 0,
                        ("row", "reason", "slo_class"),
                        (victim, "preempt", vreq.slo_class),
                    )
                    self._finish(vreq, session, slots, victim)
                    continue  # re-run admission: the freed slot goes to
                    # the waiting higher-class request immediately

            if all(s is None for s in slots):
                continue
            decode_rows = [
                r for r, s in enumerate(slots) if s is not None and not s.prefilling
            ]
            # interleaved prefill: advance ONE staged admission per chunk
            # boundary, in STAGING order (session.pending_rows) — finish the
            # earliest prompt before starting a later one, so an in-flight
            # admission's TTFT doesn't grow with later arrivals landing on
            # lower-numbered rows. With live decode streams the advance is
            # bounded by prefill_budget tokens; with none it runs to
            # completion (nothing to starve).
            prefill_rows = [
                r
                for r in session.pending_rows()
                if slots[r] is not None and slots[r].prefilling
            ]
            armed = False
            prefill_wall_us = 0  # this boundary's prefill advance (timeline)
            if prefill_rows:
                row = prefill_rows[0]
                req = slots[row]
                if req.stopped:
                    # the client died mid-admission (writer thread flagged
                    # it): abandon the rest of its prompt instead of burning
                    # one prefill chunk per boundary on a dead request and
                    # head-of-line blocking every admission staged behind it
                    self._finish(req, session, slots, row)
                    continue
                try:
                    budget = self.prefill_budget if decode_rows else None
                    t_pf = time.perf_counter()
                    remaining = session.prefill_pending(row, budget)
                    prefill_wall_us = int((time.perf_counter() - t_pf) * 1e6)
                    req.ledger.prefill_us += prefill_wall_us
                    if decode_rows:
                        engine.stats.incr("interleaved_prefill_chunks")
                except PagePoolExhausted:
                    # paged KV pool out of pages mid-admission. If no
                    # OTHER row actually HOLDS pages (slot occupancy is
                    # not enough — a staged co-tenant that never got a
                    # page can free nothing), this prompt can never fit:
                    # shed it with the standard 503 instead of spinning
                    # forever. Reclaimable prefix entries don't count
                    # either — the failed allocation already ran the
                    # reclaim hook to exhaustion.
                    if not decode_rows and not any(
                        engine.page_pool.row_holds_pages(r)
                        for r in range(engine.batch)
                        if r != row
                    ):
                        engine.stats.incr("kv_pool_shed_503")
                        self.scheduler.record(req.slo_class, "shed_pool")
                        # timeline mark: once per shed decision, cold path
                        TRACER.event(  # dlt: allow(trace-hot-emit)
                            "batch_shed", now_us(), 0,
                            ("row", "reason", "slo_class"),
                            (row, "pool_admission", req.slo_class),
                        )
                        req.error = Overloaded(retry_after_s=2)
                        self._finish(req, session, slots, row)
                        continue
                    # otherwise PARK: keep the prompt's progress and retry
                    # at the next chunk boundary. Live decode rows MUST
                    # keep stepping below — they are what finishes and
                    # frees the pages the parked admission waits for (a
                    # bare `continue` here livelocked: nobody decoded,
                    # nobody freed). With co-tenants but none decoding,
                    # yield briefly so the retry loop doesn't spin hot.
                    engine.stats.incr("kv_pool_admission_parked")
                    self.scheduler.record(req.slo_class, "park")
                    # timeline mark: once per parked boundary, cold path
                    TRACER.event(  # dlt: allow(trace-hot-emit)
                        "batch_park", now_us(), 0,
                        ("row", "pool_pages_used", "slo_class"),
                        (row, engine.page_pool.used_pages, req.slo_class),
                    )
                    remaining = None
                    if not decode_rows:
                        time.sleep(0.005)
                        continue
                except Exception as e:
                    req.error = e
                    self._finish(req, session, slots, row)
                    continue
                if remaining == 0:
                    req.prefilling = False
                    decode_rows.append(row)
                    armed = True
            if not decode_rows:
                # only prefilling rows: no decode chunk to run yet — still a
                # timeline step (admission stalls are exactly the pathology
                # the post-hoc view exists to show)
                self._timeline_step(
                    engine, slots, 0, now_us() - prefill_wall_us,
                    prefill_wall_us, spec=False,
                )
                continue
            # a row at pos == seq_len-1 has zero decode headroom: finish it
            # (the request keeps what it generated) instead of flooring the
            # chunk clamp at 1 and letting session.step's overrun guard fail
            # every co-batched request — reachable for library users driving
            # the Batcher directly; the HTTP path's budget clamp never gets
            # here. Prefilling rows are parked at seq_len by construction and
            # must NOT be swept up by this check.
            # ... and a row whose writer thread set `stopped` between
            # chunks (client gone, stream cancelled) retires HERE, at the
            # chunk boundary, instead of decoding up to a full extra chunk
            # before the consume loop sees the flag — post-stop tokens are
            # pure overrun waste
            for row in list(decode_rows):
                req = slots[row]
                if req.stopped or session.seq_len - 1 - int(session.pos[row]) <= 0:
                    self._finish(req, session, slots, row)
                    decode_rows.remove(row)
            if not decode_rows:
                continue
            # chunk size: ramp to 8 right after an admission finishes its
            # prefill (a fresh request's first tokens — and a tiny request's
            # only tokens — reach the client after ~8 steps, not a full
            # chunk). The ramp alternates: never two ramped chunks in a row,
            # so sustained admission traffic costs at most half the chunks
            # (the round-4 loop re-ramped on EVERY admission and could run
            # at chunk=8 permanently). The clamp is only the HARD seq_len
            # headroom — a row hitting its own max_new mid-chunk just has
            # its surplus tokens discarded and its slot released (no more
            # shrinking every co-tenant's chunks to the smallest remaining
            # budget, which fragmented steady-state traffic into 1-2-token
            # dispatches, each a ~75-100 ms tunnel round trip).
            headroom = min(
                session.seq_len - 1 - int(session.pos[row]) for row in decode_rows
            )
            # speculative round (runtime/speculative.py): when every decode
            # row is greedy with a full verify bucket of headroom, draft per
            # row from its delivered context (prompt ids + streamed tokens)
            # and verify all rows in ONE dispatch — rows whose draft came up
            # empty still advance by their one greedy bonus token. A sampled
            # co-tenant, tight headroom, or an all-empty draft round falls
            # back to the plain chunk, so draft-hostile traffic keeps the
            # chunked loop's throughput.
            t_chunk = time.perf_counter()  # spans: draft + dispatch + fetch
            try:
                # drafting runs INSIDE the failure scope: a model-backed
                # draft source dispatches device work, and a wedged draft
                # engine must take the same fail-requests-and-recover path
                # as a main-engine failure — not kill the batcher thread
                spec_drafts = None
                if engine.spec_mode is not None and engine.device_decode:
                    K = engine.spec_buckets[-1]
                    if all(
                        slots[r].temperature == 0.0
                        and session.seq_len - int(session.pos[r]) >= K + 1
                        for r in decode_rows
                    ):
                        try:
                            drafts = {}
                            for r in decode_rows:
                                req = slots[r]
                                cap = min(K, req.max_new - req.n - 1)
                                drafts[r] = (
                                    engine.draft_source.draft(
                                        list(req.ids) + req.out_ids, cap
                                    )
                                    if cap > 0
                                    else []
                                )
                            if any(drafts.values()):
                                spec_drafts = drafts
                        except PagePoolExhausted:
                            # a paged DRAFT engine ran out of ITS OWN pool
                            # (a separate allocator from the main engine's)
                            # — shedding a main-batch row would free
                            # nothing there. Degrade this round to the
                            # plain chunk, the same fallback draft-hostile
                            # traffic takes.
                            engine.stats.incr("kv_pool_draft_skipped")
                            spec_drafts = None
                if spec_drafts is not None:
                    per_row = session.spec_step(spec_drafts)
                else:
                    n = min(8, self.chunk) if armed and not ramped_last else self.chunk
                    ramped_last = armed and not ramped_last
                    while n > max(headroom, 1):
                        n //= 2
                    n = max(n, 1)
                    toks = session.step(n)
                    per_row = {
                        r: [int(t) for t in toks[r]]
                        for r, s in enumerate(slots)
                        if s is not None and not s.prefilling
                    }
            except PagePoolExhausted:
                # paged KV pool out of pages mid-decode (co-tenants grew
                # into the budget together): SHED the lowest-SLO-class
                # least-progress decode row (server/scheduler.py — the
                # "whom" the ROADMAP item asked for; all-standard traffic
                # reduces to the old least-progress pick) — its pages free
                # immediately, everyone else keeps decoding. The shed
                # client gets the standard 503 + Retry-After.
                victim = self.scheduler.shed_victim(
                    [(r, slots[r].slo_class, slots[r].n) for r in decode_rows]
                )
                vreq = slots[victim]
                vreq.error = vreq.error or Overloaded(retry_after_s=1)
                self.scheduler.record(vreq.slo_class, "shed_pool")
                # timeline mark: once per shed decision, cold path
                TRACER.event(  # dlt: allow(trace-hot-emit)
                    "batch_shed", now_us(), 0,
                    ("row", "reason", "slo_class"),
                    (victim, "pool_decode", vreq.slo_class),
                )
                self._finish(vreq, session, slots, victim)
                engine.stats.incr("kv_pool_shed_503")
                continue
            except Exception as e:
                # engine failure: fail every in-flight request, then hand
                # the failure to the supervised recovery path — a cheap
                # in-place reset for a first transient stall, a full
                # teardown-and-rebuild (fresh pool/prefix cache/sentinel,
                # re-warmed ladder) for sticky stalls, fatal sanitizer
                # breaches, and unknown engine exceptions
                # (runtime/supervisor.py). THIS thread owns the engine's
                # dispatches, so the rebuild is race-free here; while it
                # runs, /health reports `recovering` (503) and new
                # admissions shed.
                # classify + pre-transition FIRST: by the time any failed
                # request's 500 reaches its client, /health must already
                # say `recovering` — a client that polls (or instantly
                # retries) after its 500 must never read a stale `serving`
                # and then get shed by the rebuild it didn't know about
                entered = self.state.recover_enter(e)
                for row, req in enumerate(slots):
                    if req is not None:
                        req.error = e
                        self._finish(req, session, slots, row)
                self.state.recover(exc=e, entered=entered)
                engine = self.state.engine  # a rebuild swaps the object
                session = BatchSession(engine)
                continue
            chunk_dur_us = int((time.perf_counter() - t_chunk) * 1e6)
            preempted_last = False  # a decode chunk ran: the next boundary
            # may preempt again if a higher-class waiter is still parked
            t_chunk_us = to_us(t_chunk)
            self._timeline_step(
                engine, slots, len(decode_rows), t_chunk_us, chunk_dur_us,
                spec=spec_drafts is not None,
            )
            for row, req in enumerate(slots):
                if req is None or req.prefilling or row not in per_row:
                    continue
                # one span per row per chunk through the pre-bound emitters
                # (a tuple append each; the chunk wall is shared — per-row
                # attribution is the row's token count / acceptance)
                if spec_drafts is not None:
                    req.ledger.spec_us += chunk_dur_us
                    req.ledger.spec_accepted_tokens += max(len(per_row[row]) - 1, 0)
                    if req._em_spec is not None:
                        req._em_spec(
                            t_chunk_us, chunk_dur_us,
                            len(spec_drafts.get(row) or ()),
                            max(len(per_row[row]) - 1, 0),
                        )
                else:
                    req.ledger.decode_us += chunk_dur_us
                    if req._em_decode is not None:
                        req._em_decode(t_chunk_us, chunk_dur_us, len(per_row[row]))
                row_toks = per_row[row]
                gr = req.grammar_session
                for i, t in enumerate(row_toks):
                    req.n += 1
                    req.out_ids.append(t)
                    if gr is not None:
                        # the host session is authoritative: re-advance it
                        # from the fetched token before the next chunk's
                        # state vector is assembled (the in-graph carry is
                        # only its traced mirror)
                        gr.advance(t)
                    try:
                        req.emit.put_nowait(t)
                    except queue.Full:
                        # this client is EMIT_DEPTH tokens behind its writer
                        # — drop that row only; co-batched requests and the
                        # engine are unaffected (the writer thread owns the
                        # socket, so a merely-slow client costs nothing here)
                        req.error = req.error or RuntimeError(
                            "client fell too far behind the token stream"
                        )
                        req.stopped = True
                    if (
                        req.stopped or req.n >= req.max_new
                        or t in req.eos_ids
                        or (gr is not None and (gr.done or gr.at_terminal))
                    ):
                        # a grammar TERMINAL stop (the DFA reached a state
                        # where only EOS remains legal) retires the row
                        # exactly like EOS: the token that got it there was
                        # DELIVERED — it lands in the goodput ledger as
                        # generated, and the chunk tail past it is ordinary
                        # overrun, not a new waste class.
                        # surplus tokens past max_new in this chunk are
                        # discarded; the row parks (session.release) so
                        # co-tenants keep full-size chunks. The eos_ids
                        # check is the row-local EOS signal: without it the
                        # loop decodes up to a full extra chunk before the
                        # writer thread's `stopped` flag is visible,
                        # inflating req.n and burning decode compute. The
                        # chunk tail past the stop WAS decoded by the
                        # engine — without this count it would appear in
                        # neither generated nor discarded tokens
                        req.n_overrun += len(row_toks) - i - 1
                        self._finish(req, session, slots, row)
                        break


class ApiState:
    """Engine + tokenizer + cache shared by all requests (serialized)."""

    def __init__(self, engine: InferenceEngine, tokenizer: Tokenizer, args):
        self.engine = engine
        self.tokenizer = tokenizer
        self.args = args
        self.lock = threading.Lock()
        self._closed = False
        # supervised engine lifecycle (runtime/supervisor.py): decides
        # reset-vs-rebuild per failure, owns the recovering/failed state
        # /health reports, the restart budget, and the
        # dlt_supervisor_transitions_total counters
        from ..runtime.supervisor import EngineSupervisor

        self.supervisor = EngineSupervisor(self._rebuild_engine)
        # replica-side poison-request quarantine (server/quarantine.py):
        # strikes fingerprints implicated in engine failures, refuses
        # quarantined ones with 422 BEFORE they touch the engine, and
        # reports implications in 5xx headers + /health
        self.quarantine = QuarantineLedger()
        # per-request goodput rollup (runtime/telemetry.py): every
        # completed, shed, or retried request folds its ledger in —
        # /metrics serves dlt_goodput_tokens_per_s +
        # dlt_wasted_tokens_total{reason=...} from here (both broken down
        # by slo_class, server/scheduler.py)
        self.goodput = GoodputAggregator()
        # warm-drain-handoff tracker (server/scheduler.py): per-request
        # router-compatible prefix chain keys with hit counts, served at
        # GET /debug/hot_prefixes so the gateway's autoscaler can re-home
        # affinity BEFORE draining this replica
        self.hot_prefixes = HotPrefixTracker()
        # structured output (runtime/grammar.py): one request-format
        # compiler shared by every handler thread — FNV-keyed LRU over
        # DLT_GRAMMAR_CACHE_MB, so a fleet of identically-constrained
        # requests compiles its grammar once. None when the engine serves
        # unconstrained (mesh/host-decode, or DLT_GRAMMAR=0): any
        # response_format then 400s in _compile_grammar.
        from ..runtime.grammar import GrammarCompiler

        self.grammar_compiler = (
            GrammarCompiler(tokenizer, engine.cfg.vocab_size)
            if engine.grammar is not None
            else None
        )
        self._grammar_lock = threading.Lock()
        # crash-safe drain state (server/recovery.py): the gateway that
        # drains this replica also POSTs /admin/drain_hint so the replica
        # itself remembers it is draining (and WHO drained it, operator
        # vs autoscaler); /health carries it back, and a warm-restarting
        # gateway restores draining flags + autoscaler drain ownership
        # from there instead of silently re-admitting the replica
        self.draining_hint: dict | None = None
        # serialized path's in-flight ledger (complete/_complete_once talk
        # through it; the serialized path runs under self.lock)
        self._inflight_ledger: GoodputLedger | None = None
        self.sampler = Sampler(
            engine.cfg.vocab_size,
            args.temperature,
            args.topp,
            args.seed if args.seed is not None else 12345,
        )
        template_type = (
            ChatTemplateGenerator.parse_type(args.chat_template)
            if args.chat_template
            else TEMPLATE_UNKNOWN
        )
        self.stops = [
            tokenizer.piece(t).decode("utf-8", errors="replace")
            for t in tokenizer.eos_token_ids
        ]
        self.template = ChatTemplateGenerator(
            template_type, tokenizer.chat_template, self.stops[0] if self.stops else ""
        )
        # batch serving: engines with batch > 1 get a Batcher that groups
        # concurrent requests into one generate_batch call — on every
        # execution path, including tp/pp meshes (per-row positions thread
        # through the shard_map pipeline); batch == 1 keeps the serialized
        # path with the naive prefix cache. --host-decode requests the
        # bit-parity host sampler, which only the serialized path has
        # (generate_batch samples on-device) — honor it by serving
        # serialized instead of silently dropping the parity guarantee.
        host_decode = getattr(args, "host_decode", False)
        self.batcher = Batcher(self) if engine.batch > 1 and not host_decode else None
        if engine.batch > 1 and host_decode:
            print(
                "⚠️  --host-decode serves requests serialized (batched serving "
                "samples on-device); concurrent requests will queue"
            )
        # disaggregated serving (server/disagg.py over the KV movement
        # layer, runtime/kv_transport.py): role + the decode worker's
        # prefill-tier client. The client exists only when it can actually
        # work — decode role, peers named, a prefix cache to land shipped
        # KV in. Both KV layouts serve both roles now: paged workers
        # gather/scatter pool pages through the warmed page_extract /
        # page_insert programs.
        from .disagg import DisaggClient, resolve_peers, resolve_role

        self.role = resolve_role(getattr(args, "role", None))
        peers = resolve_peers(getattr(args, "prefill_peer", None))
        self.disagg = None
        if self.role == "decode" and peers and engine.prefix_cache is not None:
            self.disagg = DisaggClient(self, peers)
        elif self.role == "decode" and not peers:
            print(
                "⚠️  --role decode without --prefill-peer serves prompts "
                "locally (unified behavior)"
            )
        # tiered KV store (runtime/kv_tiering.py): eviction demotes down
        # the HBM -> host RAM -> disk -> peer-fleet ladder and admission
        # misses promote back up it. Any role runs it (tiers 1-2 are host
        # memory; the tier-3 serve side is host memory too) — None unless
        # some tier is configured via the DLT_KV_*_TIER_* knobs.
        from ..runtime.kv_tiering import TieredKvStore

        self.kv_tier = TieredKvStore.build(engine, goodput=self.goodput)
        engine.kv_tier = self.kv_tier  # hbm_ledger's host_tier section
        if self.kv_tier is not None and engine.prefix_cache is not None:
            engine.prefix_cache.tier = self.kv_tier

    def kv_tier_payload(self, ids, have_keys=()):
        """The same-process fleet-cache provider contract (the tier-3 twin
        of `prefill_extract`): serve the longest held host/disk-tier
        bucket as SERIALIZED payload bytes, so the requester's verify
        gate sees the same bytes a socket would carry. None = not held."""
        if self.kv_tier is None:
            return None
        return self.kv_tier.serve_fetch(list(ids), have_keys=tuple(have_keys))

    def _note_prefix_footprint(self, chain, ids):
        """Attach the tokenized cacheable-prefix footprint — pages plus
        STORED-WIDTH bytes (``_slice_nbytes`` reads the pool's real dtype,
        so int8 caches report quantized bytes) — to this request's chain
        keys in the hot-prefix tracker. The size half of the autoscaler's
        size-aware warm-handoff ranking."""
        pc = self.engine.prefix_cache
        if not chain or pc is None:
            return
        from .disagg import prefill_boundary

        P = prefill_boundary(len(ids), self.engine.cfg.seq_len)
        if P <= 0:
            return
        pages = P // pc.page_pool.page_size if pc.paged else 0
        self.hot_prefixes.note_size(
            chain, pages, pc._slice_nbytes(self.engine, P)
        )

    def prefill_extract(self, ids, have_keys=(), trace_id=None):
        """The same-process device-transport provider contract
        (runtime/kv_transport.py register_device_peer): run the prefill-
        worker core and hand the extracted segments over as device arrays —
        zero host serialization between colocated roles. Raises on
        non-prefill roles / bad input exactly like the HTTP handler 4xxs."""
        from .disagg import run_prefill_arrays

        if self.role != "prefill":
            raise OSError("this replica does not serve role=prefill")
        header, segments = run_prefill_arrays(
            self, list(ids), have_keys=tuple(have_keys)
        )
        ks = [k for _, k, _ in segments]
        vs = [v for _, _, v in segments]
        if len(ks) == 1:
            return header, ks[0], vs[0]
        return header, ks, vs

    def _record_ledger(
        self, ledger: GoodputLedger, trace, waste_reason=None,
        count_request: bool = True,
    ):
        """Fold a finished request's (or failed attempt's) ledger into the
        process aggregate and attach it to the request trace — failures
        land `always` so /debug/trace reconstructs them unsampled."""
        self.goodput.record(ledger, waste_reason, count_request=count_request)
        if trace is not None:
            trace.event(
                "ledger", now_us(), 0, LEDGER_TRACE_KEYS, ledger.trace_vals(),
                always=ledger.outcome != "ok",
            )

    def _compile_grammar(self, params: dict):
        """Resolve a request's ``response_format`` to a CompiledGrammar
        (None = unconstrained; the OpenAI-style ``{"type": "text"}`` is
        explicit unconstrained). Raises GrammarError — a 400 CLIENT error
        the handler maps before the poison-strike arm: a malformed schema
        must never cost a quarantine strike or an error-outcome ledger.
        The compile itself runs under a lock (the LRU is shared across
        handler threads); cache hits make it a dict probe."""
        rf = params.get("response_format")
        if rf is None or (isinstance(rf, dict) and rf.get("type") == "text"):
            return None
        if self.grammar_compiler is None:
            raise GrammarError(
                "response_format is not supported on this replica: "
                "grammar-constrained decoding needs a single-chip "
                "device-decode engine with DLT_GRAMMAR enabled"
            )
        with self._grammar_lock:
            return self.grammar_compiler.compile_request(rf)

    def complete_batched(self, params: dict, emit, client_visible: bool = True,
                         trace=None):
        """One request's slice of a batched generation: encode, submit to the
        Batcher, stream deltas from this row's tokens as they arrive.
        Returns (full_text, n_prompt_tokens, n_completion_tokens, ledger).
        `client_visible=False` widens stall-retry eligibility exactly like
        `complete` (see there). `trace` (runtime/tracing.py Trace) threads
        the request's span context through the Batcher and the session."""
        t_req0 = now_us()
        tok = self.tokenizer
        items = [ChatItem(m["role"], m["content"]) for m in params["messages"]]
        prompt = self.template.generate(items, True)
        ids = tok.encode(prompt.content, is_start=True)
        seq_len = self.engine.cfg.seq_len
        # batch mode needs at least one decode slot past the prompt (the
        # serialized path's boundary case of a seq_len-exact prompt would
        # otherwise surface as a batch-wide engine error)
        if len(ids) >= seq_len:
            raise PromptTooLong(
                f"prompt ({len(ids)} tokens) exceeds the context window ({seq_len})"
            )
        # structured output: compile response_format BEFORE any reservation
        # or engine work — a malformed body raises GrammarError here and
        # costs neither quota nor a ledger outcome (the handler's 400 owns
        # it, exactly like PromptTooLong above)
        grammar = self._compile_grammar(params)
        max_tokens = params.get("max_tokens", -1)
        budget = max_tokens if max_tokens and max_tokens > 0 else seq_len
        budget = max(1, min(budget, seq_len - len(ids)))
        klass = resolve_slo_class(params.get("slo_class"))
        # supervised-recovery shed (runtime/supervisor.py): while the
        # engine is being rebuilt (or the restart budget is exhausted) a
        # request must fail fast — the gateway's breaker is already
        # routing away on the 503ing /health; queueing here would just rot
        if self.supervisor.state != "serving":
            raise Overloaded(retry_after_s=2)
        # end-to-end deadline (server/scheduler.py resolve_deadline_ms,
        # threaded by the handler as a monotonic instant): a request whose
        # budget is already gone must not cost a single prefill token
        deadline = params.get("_deadline")
        if deadline is not None and time.monotonic() > deadline:
            self.engine.stats.incr("deadline_shed")
            self._record_ledger(
                GoodputLedger(
                    prompt_tokens=len(ids), outcome="deadline",
                    slo_class=klass,
                ),
                trace, waste_reason="deadline",
            )
            raise DeadlineExceeded("deadline passed before admission")
        # load shedding: past the backlog cap — or past this CLASS's quota
        # share of it (server/scheduler.py) — a request would sit in a
        # queue it will likely rot in: fail fast with 503 + Retry-After
        # (roughly one chunk's worth of drain time) instead of burning the
        # client's patience and a slot's worth of queue memory. The check
        # RESERVES a quota slot atomically (a concurrent burst must not
        # all pass a stale zero); the reservation transfers to the Batcher
        # at submit and is released on any failure before that.
        if not self.batcher.try_reserve(klass):
            self.engine.stats.incr("shed_503")
            self.batcher.scheduler.record(klass, "shed_backlog")
            # shed requests land in the goodput ledger too (zero tokens
            # moved, but the shed storm must be visible as an outcome)
            self._record_ledger(
                GoodputLedger(
                    prompt_tokens=len(ids), outcome="shed", slo_class=klass
                ),
                trace,
            )
            raise Overloaded(retry_after_s=1)
        pending_kv = None
        try:
            # disaggregated prefill (server/disagg.py): fetch the prompt's
            # leading-bucket KV BEFORE admission; the INSERT is deferred to
            # the Batcher loop (engine thread — a paged insert donates the
            # live pool), which applies it right before begin_admit so the
            # ordinary match/splice picks it up. Runs after the shed check
            # (never burn a prefill worker on a shed request); degrades to
            # local prefill on any failure — zeros ride the ledger.
            disagg_walls = self.disagg.fetch(ids, trace) if self.disagg else None
            if disagg_walls is not None:
                pending_kv = disagg_walls.pop("pending_kv", None)
            # tiered-KV promotion (runtime/kv_tiering.py): when the
            # prefill tier shipped nothing, try the demotion ladder —
            # host RAM, then disk, then the fleet cache. Same deferred-
            # insert contract as the disagg pending; degrades to local
            # prefill on any failure. note_chain teaches the prefetch-
            # hint index what tokens this router chain resolves to.
            tier_walls = None
            self._note_prefix_footprint(params.get("_chain") or (), ids)
            if self.kv_tier is not None:
                self.kv_tier.note_chain(params.get("_chain") or (), ids)
                if pending_kv is None:
                    tier_walls = self.kv_tier.fetch(ids, trace)
                    pending_kv = tier_walls.pop("pending_kv", None)

            base = []
            if prompt.public_prompt:
                emit(prompt.public_prompt)
                base.append(prompt.public_prompt)
        except BaseException:
            # the reservation never reached submit (e.g. the client died
            # on the public-prompt emit): release it, or the class's
            # quota leaks one slot per failed pre-admission step
            self.batcher.release_reservation(klass)
            if pending_kv is not None:
                pending_kv.abandon()
            raise

        req_box = []
        deltas_box = []
        times_box = [[None, None]]  # [first_token_perf, last_token_perf]

        def make_req():
            """Fresh request + decode state + delta buffer (a stall retry
            must not inherit the failed attempt's UTF-8 carry, stop-string
            window, or partial text)."""
            dec = tok.stream_decoder()  # per-row UTF-8 carry state
            detector = EosDetector(
                tok.eos_token_ids,
                self.stops,
                max((len(s) for s in self.stops), default=0),
                max((len(s) for s in self.stops), default=0),
            )
            deltas = []
            deltas_box[:] = [deltas]
            times = [None, None]
            times_box[:] = [times]

            def on_token(t):
                nowp = time.perf_counter()  # TTFT/per-token histograms
                if times[0] is None:
                    times[0] = nowp
                times[1] = nowp
                piece = dec.decode(t)
                eos_type = detector.append(t, piece)
                if eos_type != EOS_MAYBE:
                    delta = detector.get_delta()
                    if delta:
                        emit(delta)
                        deltas.append(delta)
                    detector.reset()
                if eos_type == EOS_FOUND:
                    req_box[0].stopped = True

            req = _BatchReq(
                ids, budget,
                params.get("temperature", self.args.temperature),
                params.get("top_p", self.args.topp),
                params.get("seed"),
                on_token,
                eos_ids=frozenset(tok.eos_token_ids),
                trace=trace,
                slo_class=klass,
                deadline=deadline,
                grammar=grammar,
            )
            req_box[:] = [req]
            return req

        from ..runtime.telemetry import StallError

        def fail_ledger(req, outcome):
            """A failed request (or failed attempt): every token it decoded
            is waste — nothing reached a successful response. Deliberately
            does NOT touch pending_kv: a stall-retried attempt's deferred
            insert must survive into attempt 2 (the terminal paths abandon
            it explicitly)."""
            led = req.ledger
            led.outcome = outcome
            led.generated_tokens = 0
            led.discarded_tokens += req.n + req.n_overrun
            return led

        for attempt in range(2):
            try:
                req = make_req()
            except BaseException:
                if attempt == 0:  # submit never ran: the reservation is
                    # still ours to give back (attempt 1's was already
                    # consumed by the first attempt's drain)
                    self.batcher.release_reservation(klass)
                if pending_kv is not None:
                    pending_kv.abandon()
                raise
            req.ledger.retries = attempt
            if disagg_walls is not None:
                req.ledger.remote_prefill_us = disagg_walls["remote_prefill_us"]
                req.ledger.kv_transfer_us = disagg_walls["kv_transfer_us"]
                req.ledger.kv_transfer_path = disagg_walls.get(
                    "kv_transfer_path", ""
                )
            if tier_walls is not None:
                req.ledger.promotion_us = tier_walls["promotion_us"]
            # deferred external-KV insert: the Batcher loop applies it on
            # the engine thread right before this request's admission
            # (idempotent — a stall retry's second attempt reuses it)
            req.kv_external = pending_kv
            try:
                self.batcher.submit(req)
                break
            except StallError:
                # the decode watchdog fired mid-chunk: the Batcher loop
                # already reset the engine and rebuilt the session. Retry
                # IN PLACE exactly once — safe when nothing reached this
                # client yet (streamed bytes cannot be replayed without
                # duplication), or always on the non-stream path
                # (client_visible=False: emit is a no-op and the response
                # is built from the final attempt's deltas alone)
                self.engine.stats.incr("stall_resets")
                if attempt == 0 and (req.n_out == 0 or not client_visible):
                    self.engine.stats.incr("stall_retries")
                    # token accounting for the abandoned attempt — the
                    # REQUEST outcome is the final attempt's to report
                    self._record_ledger(
                        fail_ledger(req, "error"), trace,
                        waste_reason="stall_retry", count_request=False,
                    )
                    continue
                if pending_kv is not None:
                    pending_kv.abandon()  # terminal failure: drop the pin
                self._record_ledger(fail_ledger(req, "error"), trace)
                raise
            except Overloaded:
                # pool-pressure shed or class preemption mid-flight (the
                # Batcher picked this row as the victim) — distinct from
                # the backlog shed above; a preempted row's decoded tokens
                # are labeled "preempt" waste so the scheduler's cost is
                # its own goodput line
                if pending_kv is not None:
                    pending_kv.abandon()
                self._record_ledger(
                    fail_ledger(req, "shed"), trace,
                    waste_reason="preempt" if req.preempted else None,
                )
                raise
            except DeadlineExceeded:
                # the Batcher shed it at a chunk boundary (or pre-prefill):
                # every token it decoded is `deadline` waste — compute for
                # an answer nobody was still waiting for
                if pending_kv is not None:
                    pending_kv.abandon()
                self._record_ledger(
                    fail_ledger(req, "deadline"), trace,
                    waste_reason="deadline",
                )
                raise
            except ClientDisconnected:
                if pending_kv is not None:
                    pending_kv.abandon()
                self._record_ledger(fail_ledger(req, "client_gone"), trace)
                raise
            except Exception:
                if pending_kv is not None:
                    pending_kv.abandon()
                self._record_ledger(fail_ledger(req, "error"), trace)
                raise
        if pending_kv is not None:
            # applied by the Batcher at admission (abandon is then a no-op);
            # a request retired WITHOUT admission must still drop the pin
            pending_kv.abandon()
        # n_out counts tokens the writer actually delivered (the EOS token
        # included) — req.n also counts post-stop overrun decoded before the
        # step loop noticed, which must not inflate usage accounting
        self.supervisor.note_ok()  # a served request clears stall strikes
        self.engine.stats.incr("requests_completed")
        led = req.ledger
        led.outcome = "ok"
        led.generated_tokens = req.n_out
        # discarded = decoded-but-undelivered (n - n_out) PLUS the chunk
        # tail the engine decoded past the stop point (n_overrun, which
        # never entered req.n) — both fold into the aggregate's "overrun"
        # waste reason for ok outcomes (runtime/telemetry.py)
        led.discarded_tokens += max(req.n - req.n_out, 0) + req.n_overrun
        self._record_ledger(led, trace)
        times = times_box[0]
        if times[0] is not None:
            # per-request latency histograms: TTFT from request arrival to
            # the first delivered token (queue wait included — the client's
            # view), per-output-token from the delivery span. Observed
            # twice: the unlabeled fleet-facing totals (unchanged shape)
            # and the {slo_class} breakdown rows the autoscaler's per-class
            # attainment reads (server/scheduler.py, PR 12 follow-on)
            ttft = max((to_us(times[0]) - t_req0) / 1e3, 0.0)
            self.engine.stats.observe("ttft_ms", ttft)
            self.engine.stats.observe(
                "ttft_ms", ttft, labels={"slo_class": klass}
            )
            if req.n_out > 1:
                tpot = (times[1] - times[0]) * 1e3 / (req.n_out - 1)
                self.engine.stats.observe("tpot_ms", tpot)
                self.engine.stats.observe(
                    "tpot_ms", tpot, labels={"slo_class": klass}
                )
        return "".join(base + deltas_box[0]), len(ids), req.n_out, led

    def complete(self, params: dict, emit, client_visible: bool = True,
                 trace=None):
        """Run one completion; calls emit(delta_text) per safe-to-send chunk.
        Returns (full_text, n_prompt_tokens, n_completion_tokens, ledger).

        A `StallError` from the decode watchdog (wedged device step) gets
        ONE bounded in-place retry on the recovered engine — but only when
        nothing reached the client yet: a half-streamed response cannot be
        transparently replayed. `client_visible=False` (the non-stream
        handler, whose emit is a no-op and whose response is built solely
        from the return value) makes the retry unconditionally safe."""
        from ..runtime.telemetry import StallError

        # supervised-recovery shed: same contract as the batched path
        if self.supervisor.state != "serving":
            raise Overloaded(retry_after_s=2)

        emitted = [False]

        def traced_emit(delta):
            emitted[0] = True
            emit(delta)

        def fail_ledger(outcome):
            """Finalize the in-flight attempt's ledger on a failure: every
            token a failed request decoded is waste (partial stream bytes
            are a truncated response, not delivered goodput)."""
            led = self._inflight_ledger
            self._inflight_ledger = None
            if led is None:
                led = GoodputLedger()
            led.outcome = outcome
            led.generated_tokens = 0
            return led

        for attempt in range(2):
            try:
                return self._complete_once(
                    params, traced_emit, trace=trace, retried=attempt > 0
                )
            except StallError:
                # _complete_once's failure path already ran recover()
                # (engine reset + prefix cache dropped), so the retry starts
                # clean and re-prefills from position 0 (the retry builds a
                # fresh buffer, so nothing from the failed attempt leaks
                # into the result)
                self.engine.stats.incr("stall_resets")
                if attempt > 0 or (emitted[0] and client_visible):
                    self._record_ledger(fail_ledger("error"), trace)
                    raise
                self.engine.stats.incr("stall_retries")
                self._record_ledger(
                    fail_ledger("error"), trace,
                    waste_reason="stall_retry", count_request=False,
                )
            except PromptTooLong:
                # client-input 400, raised before any engine work: not an
                # error OUTCOME — the batched path records nothing for
                # these either, and error dashboards must not alarm on it
                raise
            except GrammarError:
                # malformed response_format: same client-input 400 class as
                # PromptTooLong (raised before any engine work) — never an
                # error outcome, never a poison strike
                raise
            except DeadlineExceeded:
                self._record_ledger(
                    fail_ledger("deadline"), trace, waste_reason="deadline"
                )
                raise
            except ClientDisconnected:
                self._record_ledger(fail_ledger("client_gone"), trace)
                raise
            except Exception:
                self._record_ledger(fail_ledger("error"), trace)
                raise

    def _complete_once(self, params: dict, emit, trace=None, retried=False):
        engine, tok = self.engine, self.tokenizer
        messages = params["messages"]
        # full-prompt serving over the radix prefix cache: every request
        # encodes its WHOLE templated conversation and resets the live
        # cache; the engine's prefix cache splices whatever prefix any
        # earlier request (this conversation's prior turn, or an unrelated
        # user sharing a system prompt) already published — multi-
        # conversation correct where NaiveCache thrashed on interleaving
        engine.reset()

        items = [ChatItem(m["role"], m["content"]) for m in messages]
        prompt = self.template.generate(items, True)
        ids = tok.encode(prompt.content, is_start=True)
        seq_len = engine.cfg.seq_len
        if len(ids) - 1 >= seq_len:
            # the reference clamps silently and returns an empty completion
            # (dllama-api.cpp:390-391); surface it as a client error instead
            raise PromptTooLong(
                f"prompt ({len(ids)} tokens) exceeds the context window ({seq_len})"
            )

        # structured output: compile BEFORE any engine work (GrammarError
        # here is a client 400, like PromptTooLong above); the session —
        # arena span + per-row DFA state — is built inline further down:
        # the serialized path runs under self.lock, so this IS the engine
        # thread and the install is race-free
        grammar = self._compile_grammar(params)
        prompt_end = len(ids) - 1
        max_tokens = params.get("max_tokens", -1)
        max_pred = min(prompt_end + max_tokens, seq_len) if max_tokens and max_tokens > 0 else seq_len
        # end-to-end deadline: shed BEFORE spending the prefill when the
        # budget is already gone (the serialized path's queue is the wait
        # on state.lock — it can eat the whole budget under load)
        deadline = params.get("_deadline")
        if deadline is not None and time.monotonic() > deadline:
            engine.stats.incr("deadline_shed")
            self._inflight_ledger = GoodputLedger(
                prompt_tokens=len(ids),
                slo_class=resolve_slo_class(params.get("slo_class")),
            )
            raise DeadlineExceeded("deadline passed before prefill")
        # disaggregated prefill (server/disagg.py): the fetched KV lands in
        # the prefix cache and engine.generate's ordinary prefill match
        # splices it; any failure degrades to local prefill (zeros
        # returned). The serialized path runs under self.lock, so the
        # deferred insert applies inline — this IS the engine thread here.
        disagg_walls = self.disagg.fetch(ids, trace) if self.disagg else None
        applied_external = False
        if disagg_walls is not None:
            pending_kv = disagg_walls.pop("pending_kv", None)
            if pending_kv is not None:
                pending_kv.apply(self)
                applied_external = True
        # tiered-KV promotion (runtime/kv_tiering.py): host/disk/peer
        # ladder when the prefill tier shipped nothing. Inline apply —
        # under self.lock this IS the engine thread.
        tier_walls = None
        self._note_prefix_footprint(params.get("_chain") or (), ids)
        if self.kv_tier is not None:
            self.kv_tier.note_chain(params.get("_chain") or (), ids)
            if not applied_external:
                tier_walls = self.kv_tier.fetch(ids, trace)
                pending_tier = tier_walls.pop("pending_kv", None)
                if pending_tier is not None:
                    pending_tier.apply(self)

        buffer = []
        if prompt.public_prompt:
            emit(prompt.public_prompt)
            buffer.append(prompt.public_prompt)

        tok.reset_decoder()
        detector = EosDetector(
            tok.eos_token_ids,
            self.stops,
            max((len(s) for s in self.stops), default=0),
            max((len(s) for s in self.stops), default=0),
        )
        self.sampler.set_temp(params.get("temperature", self.args.temperature))
        if params.get("seed") is not None:
            self.sampler.set_seed(params["seed"])
        self.sampler.topp = params.get("top_p", self.args.topp)

        # per-request goodput ledger: walls + token outcomes; parked on the
        # instance (serialized path runs under self.lock) so `complete` can
        # finalize it if this attempt dies mid-generate
        led = GoodputLedger(
            prompt_tokens=len(ids), retries=1 if retried else 0,
            slo_class=resolve_slo_class(params.get("slo_class")),
        )
        if disagg_walls is not None:
            led.remote_prefill_us = disagg_walls["remote_prefill_us"]
            led.kv_transfer_us = disagg_walls["kv_transfer_us"]
            led.kv_transfer_path = disagg_walls.get("kv_transfer_path", "")
        if tier_walls is not None:
            led.promotion_us = tier_walls["promotion_us"]
        self._inflight_ledger = led
        spec_accept_0 = engine.stats.counters_snapshot().get(
            "spec_accepted_tokens", 0
        )

        # drive the engine's generation loop (chunked on-device decode — one
        # host round trip per K tokens; with on-device sampling the RNG
        # stream differs from the reference's host xorshift*, temperature 0
        # remains bit-identical)
        state = {"stop": False, "n": 0}

        def on_token(t):
            state["n"] += 1
            # running decoded count: if this attempt fails, every decoded
            # token is waste — `complete` reads it off the parked ledger
            led.discarded_tokens = state["n"]
            piece = tok.decode(t)
            eos_type = detector.append(t, piece)
            if eos_type != EOS_MAYBE:
                delta = detector.get_delta()
                if delta:
                    emit(delta)
                    buffer.append(delta)
                detector.reset()
            if eos_type == EOS_FOUND:
                state["stop"] = True

        def stop_fn(t):
            if state["stop"]:
                return True
            # per-chunk-boundary deadline check (generate consults stop_fn
            # between decode chunks): tokens past the deadline are waste
            if deadline is not None and time.monotonic() > deadline:
                state["deadline_hit"] = True
                return True
            return False

        gr_sess = None
        if grammar is not None:
            from ..runtime.grammar import GrammarSession

            gr_sess = GrammarSession(engine.grammar, grammar)
        try:
            # the engine emits this request's prefill/decode/spec spans
            # through its trace context for the duration of the generate
            engine.trace = trace
            res = engine.generate(
                ids, max_pred, sampler=self.sampler, pos_start=0,
                on_token=on_token, stop_fn=stop_fn, grammar=gr_sess,
            )
        except ClientDisconnected:
            # the CLIENT dropped mid-stream (emit raised) — the engine and
            # the published prefixes are fine; this turn was never pushed
            raise
        except Exception as e:
            # an ENGINE failure leaves the KV cache holding a prefix that
            # was never fully written — drop the live cache AND the prefix
            # cache (an in-flight publish may descend from the failed
            # computation) so the next request starts clean; the
            # supervisor classifies the failure (reset vs full rebuild)
            self.recover(exc=e)
            raise
        finally:
            engine.trace = None
            if gr_sess is not None:
                gr_sess.close()  # release the arena span; the compiled
                # grammar stays hot in the ApiState LRU
        if state.get("deadline_hit"):
            # generation stopped because the deadline passed mid-decode:
            # every decoded token is `deadline` waste (the parked ledger
            # carries them as discarded; complete() finalizes it)
            engine.stats.incr("deadline_expired")
            raise DeadlineExceeded("deadline passed mid-decode")
        # the engine published this conversation's KV into the prefix trie
        # itself (generate's post-decode publish); keep the NaiveCache-era
        # miss signal as a counter for dashboards that tracked it
        if engine.prefix_cache is not None and engine.last_prefix_hit_tokens == 0:
            engine.stats.incr("cache_miss")
        self.supervisor.note_ok()  # a served request clears stall strikes
        engine.stats.incr("requests_completed")
        # per-request latency histograms (the serialized path's twin of the
        # Batcher observes: GenerationResult already carries the walls) —
        # unlabeled totals + the {slo_class} breakdown, like the batched path
        engine.stats.observe("ttft_ms", res.ttft_us / 1e3)
        engine.stats.observe(
            "ttft_ms", res.ttft_us / 1e3, labels={"slo_class": led.slo_class}
        )
        if res.n_pred_tokens > 1:
            tpot = (res.total_us - res.ttft_us) / (res.n_pred_tokens - 1) / 1e3
            engine.stats.observe("tpot_ms", tpot)
            engine.stats.observe(
                "tpot_ms", tpot, labels={"slo_class": led.slo_class}
            )
        # finalize + fold the goodput ledger (GenerationResult carries the
        # walls; prefix-hit/spec-accepted from the engine's own accounting)
        led.prefill_us = res.prefill_us
        led.decode_us = res.decode_us
        led.prefix_hit_tokens = engine.last_prefix_hit_tokens
        led.spec_accepted_tokens = (
            engine.stats.counters_snapshot().get("spec_accepted_tokens", 0)
            - spec_accept_0
        )
        led.generated_tokens = res.n_pred_tokens
        led.discarded_tokens = max(state["n"] - res.n_pred_tokens, 0)
        led.outcome = "ok"
        self._inflight_ledger = None
        self._record_ledger(led, trace)
        text = "".join(buffer)
        return text, len(ids), res.n_pred_tokens, led

    def recover_enter(self, exc: BaseException) -> str | None:
        """Classify one engine failure and, on a rebuild verdict,
        pre-transition the supervisor to ``recovering`` — called by the
        Batcher BEFORE it fails the in-flight requests, so by the time
        any client holds its 500, ``/health`` already reports the rebuild
        (no serving->recovering flap behind the client's back). Returns
        the action for :meth:`recover`'s ``entered=`` — classification
        has stall-strike side effects and must run exactly once per
        failure. None when the replica is already closed."""
        if self._closed:
            return None
        action = self.supervisor.classify(exc)
        if action == "rebuild":
            self.supervisor.enter_recovering(type(exc).__name__)
        return action

    def recover(self, exc: BaseException | None = None,
                entered: str | None = None):
        """Supervised recovery after a failed generation. The old one-shot
        behavior (engine reset + prefix-cache drop) survives as the CHEAP
        path for transient failures; the supervisor
        (runtime/supervisor.py) escalates sticky stalls, fatal sanitizer
        breaches, unknown engine exceptions — and a reset that itself
        fails — to a full teardown-and-rebuild: fresh engine, fresh
        pool/prefix cache, re-warmed ladder, freshly sealed sentinel.
        MUST be called from the engine-owning thread (the Batcher loop /
        the serialized handler under ``self.lock``): the rebuild swaps
        ``self.engine`` under live dispatch ownership.

        The prefix cache is always cleared first: entries extracted near
        the failure may hold poisoned/unfinished KV, and a silent splice
        of one would corrupt a future request."""
        # post-mortem FIRST: the trace ring still holds the failed
        # request's spans and whatever engine events led up to the failure
        flight_record(
            "api.recover", counters=self.engine.stats.counters_snapshot()
        )
        if self.engine.prefix_cache is not None:
            self.engine.prefix_cache.clear()
        if self._closed:
            return  # teardown raced a final failure: nothing left to heal
        if entered is not None:
            action = entered  # recover_enter already classified (and, for
            # a rebuild, already holds the `recovering` state)
        else:
            action = (
                self.supervisor.classify(exc) if exc is not None else "reset"
            )
        reason = type(exc).__name__ if exc is not None else "recover"
        if action == "reset":
            try:
                self.engine.reset()
                self.supervisor.note_reset(reason)
                return
            except Exception:
                # a reset that fails on an already-wedged engine is the
                # strongest rebuild signal there is — escalate, and leave
                # the counter trail (/stats, /health) saying why
                self.engine.stats.incr("recover_reset_failed")
                reason = f"reset_failed({reason})"
        try:
            self.supervisor.recover(reason, stats=self.engine.stats)
        except Exception:
            # the rebuild itself died: the supervisor already transitioned
            # to `failed` and counted it (supervisor_rebuild_failed) — the
            # replica reports unhealthy from here on; swallowing keeps the
            # Batcher loop alive to shed what's still queued
            pass  # dlt: allow(swallowed-exception) — counted + state=failed; nothing else to do here

    def close(self):
        """Release the replica's engine-side resources: stop the Batcher
        loop (failing anything still in flight), the tiered-KV store's
        drain/prefetch loops, then close the engine — which unsubscribes
        its recompile sentinel. Without this, a server's engine lives
        forever on the Batcher's daemon thread and its SEALED fatal
        sentinel keeps killing every later engine build in the process
        (the cross-suite pollution class). Idempotent; wired to the HTTP
        server's ``shutdown()``/``server_close()``."""
        if self._closed:
            return
        self._closed = True
        if self.batcher is not None:
            self.batcher.stop()
        if self.kv_tier is not None:
            self.kv_tier.close()
        self.engine.close()

    def _rebuild_engine(self):
        """The supervisor's rebuild_fn: tear the old engine down (sentinel
        unsubscribed — a sealed fatal sentinel must never outlive its
        engine and condemn the successor's warmup), build a fresh one from
        the same resolved args (fresh KV pool, fresh prefix cache), re-run
        the warm ladder (``warmup()`` executes ``warm_plan()`` and
        re-seals a FRESH sentinel), and swap it in. Counters carry over so
        the operator trail (/stats, /health, the fleet table) stays
        monotonic across the swap; latency series and histograms restart
        (the fleet scraper re-baselines backward counters anyway)."""
        import os

        from ..cli import make_engine

        old = self.engine
        # build-then-swap: the NEW engine comes up fully (weights, warm
        # ladder, sealed sentinel) before the old one is released — a
        # rebuild that dies mid-build (bad weights path, OOM, a stall
        # inside warmup) leaves the old engine intact for the supervisor's
        # failed-state degradation instead of stranding a half-closed one.
        # Sentinel attribution is safe in the overlap: the new engine's
        # UNSEALED sentinel claims the build's compiles, so the old sealed
        # one neither counts nor (fatal) aborts them.
        engine = make_engine(self.args)
        for k, v in old.stats.counters_snapshot().items():
            engine.stats.incr(k, v)
        if not os.environ.get("DLT_NO_WARMUP"):
            engine.warmup()
        if self._closed:
            # teardown raced the rebuild (close()'s join timed out while
            # warmup ran): the fresh engine's SEALED sentinel must not
            # outlive this aborted swap — that leak is the exact class
            # this lifecycle exists to fix
            engine.close()
            raise RuntimeError("replica closed during rebuild")
        self.engine = engine
        old.close()
        if self._closed:
            # close() ran between the check above and the swap: it closed
            # the OLD engine; release the fresh one too (engine.close is
            # idempotent, so a double close from either side is safe)
            engine.close()


#: THE declared DLT_* knob surface: every environment variable the package
#: reads, whether or not it is set on this replica. `/debug/config` serves
#: it (`env_surface`) so operators can discover every knob from a running
#: box, and the `env-surface` lint rule (analysis/lint.py) statically
#: proves the list complete — an os.environ/getenv read of a DLT_* name
#: missing here (or from the docs) fails lint. Keep alphabetized.
DLT_ENV_SURFACE = (
    "DLT_BATCH_TIMELINE",
    "DLT_BATCH_TIMELINE_SAMPLE",
    "DLT_COMPILE_CACHE",
    "DLT_COMPILE_LOG_MS",
    "DLT_COST_TABLE",
    "DLT_DISAGG_PEER_BACKOFF_S",
    "DLT_DISAGG_TIMEOUT_S",
    "DLT_DRAFT_K",
    "DLT_FLIGHTREC_DIR",
    "DLT_GRAMMAR",
    "DLT_GRAMMAR_ARENA_MB",
    "DLT_GRAMMAR_CACHE_MB",
    "DLT_GRAMMAR_MAX_SPEC_KB",
    "DLT_GRAMMAR_MAX_STATES",
    "DLT_GW_RECOVER",
    "DLT_GW_RECOVER_TIMEOUT_S",
    "DLT_HBM_DRIFT_MB",
    "DLT_I8_DIMSEM",
    "DLT_KV_DISK_TIER_DIR",
    "DLT_KV_DISK_TIER_MB",
    "DLT_KV_DTYPE",
    "DLT_KV_HOST_TIER_MB",
    "DLT_KV_INTEGRITY_STRIKES",
    "DLT_KV_INTEGRITY_TTL_S",
    "DLT_KV_LAYOUT",
    "DLT_KV_PAGE",
    "DLT_KV_POOL_MB",
    "DLT_KV_TIER_PEERS",
    "DLT_KV_TRANSPORT",
    "DLT_MOE_LAYER_FOLD",
    "DLT_NO_NATIVE",
    "DLT_NO_PALLAS",
    "DLT_NO_WARMUP",
    "DLT_PALLAS_INTERPRET",
    "DLT_PEAK_HBM_GBS",
    "DLT_PEAK_TFLOPS",
    "DLT_PREFILL_PEER",
    "DLT_PREFILL_PIPELINE",
    "DLT_PREFIX_CACHE_MB",
    "DLT_PROFILE_DIR",
    "DLT_ROLE",
    "DLT_ROUTER",
    "DLT_SANITIZERS",
    "DLT_SANITIZERS_FATAL",
    "DLT_SLO_PREEMPT",
    "DLT_SPECULATIVE",
    "DLT_STALL_LOG_MS",
)


def resolved_config(state: "ApiState") -> dict:
    """The ``GET /debug/config`` payload: the RESOLVED runtime
    configuration this replica is actually serving with — after env vars,
    CLI flags, and capability fallbacks (paged->contiguous on meshes,
    spec-off on host-decode) have all been applied — so fleet debugging
    never requires shell access to the box. The gateway proxies this
    per-backend under its own ``/debug/config``."""
    import os

    eng = state.engine
    env = {
        k: v for k, v in sorted(os.environ.items())
        if k.startswith("DLT_") and "KEY" not in k and "TOKEN" not in k
    }
    pc = eng.prefix_cache
    batcher = state.batcher
    return {
        "model": MODEL_NAME,
        "engine": {
            "batch": eng.batch,
            "seq_len": eng.cfg.seq_len,
            "compute_dtype": eng.cfg.compute_dtype,
            "cache_dtype": eng.cfg.cache_dtype,
            "max_chunk": eng.max_chunk,
            "decode_chunk_size": eng.decode_chunk_size,
            "device_decode": eng.device_decode,
        },
        "kv": {
            "layout": eng.kv_layout,
            "page_size": eng.page_size,
            "pool": None if eng.page_pool is None else eng.page_pool.snapshot(),
        },
        "prefix_cache": None if pc is None else {
            "budget_bytes": pc.budget_bytes,
            "buckets": list(pc.buckets),
        },
        "speculative": {
            "mode": eng.spec_mode,
            "draft_k": eng.draft_k,
            "buckets": list(eng.spec_buckets),
        },
        # structured output (runtime/grammar.py): arena occupancy + the
        # request-format compiler's LRU counters; None when this replica
        # serves unconstrained (mesh/host-decode, or DLT_GRAMMAR=0)
        "grammar": None if eng.grammar is None else dict(
            eng.grammar.snapshot(),
            compiler=(
                state.grammar_compiler.cache_stats()
                if state.grammar_compiler is not None
                else None
            ),
        ),
        "batcher": None if batcher is None else {
            "chunk_size": batcher.chunk,
            "prefill_budget": batcher.prefill_budget,
            "max_backlog": batcher.max_backlog,
            "timeline_sample": batcher.timeline_sample,
            "scheduler": batcher.scheduler.config.snapshot(),
        },
        "role": state.role,
        "disagg": None if state.disagg is None else state.disagg.snapshot(),
        "kv_tiering": (
            None if state.kv_tier is None else state.kv_tier.snapshot()
        ),
        "supervisor": state.supervisor.config.snapshot(),
        "quarantine": {
            "limit": state.quarantine.limit,
            "ttl_s": state.quarantine.ttl_s,
        },
        "tracing": {
            "ring_capacity": TRACER.ring.capacity,
            "sample_every": TRACER.sample_every(),
        },
        "sanitizers": {
            "enabled": bool(getattr(eng, "_sanitize", False)),
            "fatal": os.environ.get("DLT_SANITIZERS_FATAL", "") not in ("", "0"),
        },
        "goodput_window_s": state.goodput.window_s,
        "env": env,
        # the DECLARED knob surface (every DLT_* var the package reads,
        # set here or not) — `env` above shows only what this replica has
        # set; this shows what COULD be set, statically lint-proven
        # complete (analysis/lint.py env-surface)
        "env_surface": list(DLT_ENV_SURFACE),
    }


class Handler(BaseHTTPRequestHandler):
    state: ApiState = None  # set by serve()
    protocol_version = "HTTP/1.1"
    _trace = None  # per-request Trace (do_POST); _json echoes its id
    _poison_fp = None  # this chat request's quarantine fingerprint

    def _poison_strike(self) -> dict | None:
        """An engine failure killed this request: strike its fingerprint
        (server/quarantine.py) and return the response headers reporting
        the implication — the gateway's retry ledger and direct clients
        both read ``X-DLT-Poison-Fp`` off the 5xx."""
        fp = self._poison_fp
        if fp is None:
            return None
        self.state.quarantine.strike(fp)
        self.state.engine.stats.incr("poison_strikes")
        return {POISON_HEADER: fp_hex(fp)}

    def log_message(self, fmt, *args):
        pass

    def _query_params(self) -> dict:
        return parse_query(self.path.partition("?")[2])

    def do_GET(self):
        route = self.path.partition("?")[0]
        if route == "/metrics":
            # Prometheus text exposition: every StepStats counter/gauge/
            # percentile series plus the TTFT / per-output-token histograms,
            # with Batcher occupancy and prefix-cache occupancy as gauges —
            # and the device-performance layer (runtime/profiling.py): the
            # dlt_hbm_bytes{component=...} ledger, dlt_mfu /
            # dlt_bw_utilization / duty-cycle roofline gauges (once a cost
            # table exists), and the TTFT/TPOT SLO-attainment gauges
            st = self.state
            extra = {}
            if st.batcher is not None:
                for k, v in st.batcher.stats().items():
                    if isinstance(v, (int, float)):  # queue_depths is the
                        extra[f"batcher_{k}"] = v    # /stats-only dict view
            pc = st.engine.prefix_cache
            if pc is not None:
                snap = pc.stats_snapshot()
                for k in ("entries", "bytes", "budget_bytes", "pinned"):
                    if k in snap:
                        extra[f"prefix_cache_{k}"] = snap[k]
            from ..runtime.profiling import metrics_view

            prof_gauges, prof_series = metrics_view(st.engine)
            extra.update(prof_gauges)
            # goodput ledger rollup (runtime/telemetry.py): delivered-token
            # rate (unlabeled total + slo_class breakdown, one gauge
            # family) + per-reason waste counters (reason totals + the
            # {reason, slo_class} breakdown rows) — the federation scraper
            # (server/fleet.py) lifts both into the per-replica table
            series = dict(prof_series)
            series["goodput_tokens_per_s"] = st.goodput.goodput_series()
            # KV movement accounting (runtime/kv_transport.py): per-path
            # transfer-wall quantiles + bytes moved — the device-vs-http
            # bench bar and any fleet dashboard read these labeled families
            kvt_rows = []
            for pth in ("device", "http"):
                pct = st.engine.stats.percentiles(f"kv_transfer_us[{pth}]")
                for q, v in sorted(pct.items()):
                    kvt_rows.append(
                        ({"path": pth, "quantile": q}, round(v, 1))
                    )
            if kvt_rows:
                series["kv_transfer_us"] = kvt_rows
            # tiered-KV promotion wall quantiles (runtime/kv_tiering.py):
            # the per-request fetch wall (dlt_promotion_us) — the ledger's
            # promotion_us field is the per-request twin
            promo_pct = st.engine.stats.percentiles("promotion_us")
            if promo_pct:
                series["promotion_us"] = [
                    ({"quantile": q}, round(v, 1))
                    for q, v in sorted(promo_pct.items())
                ]
            snap_counters = st.engine.stats.counters_snapshot()
            counter_series = {
                "wasted_tokens": st.goodput.wasted_series()
                + st.goodput.wasted_by_class_series(),
                "kv_transfer_bytes": [
                    ({"path": pth}, snap_counters.get(f"kv_transfer_bytes_{pth}", 0))
                    for pth in ("device", "http")
                ],
                # data-plane integrity outcomes, zero-filled: the corruption
                # dashboard (and its alert) exists before the first corrupt
                # transfer ever lands — dlt_kv_integrity_total{outcome=...}
                "kv_integrity": [
                    ({"outcome": oc}, snap_counters.get(f"kv_integrity_{oc}", 0))
                    for oc in ("verified", "rejected")
                ],
            }
            if st.kv_tier is not None:
                # per-tier hit outcomes, zero-filled: the tiering
                # dashboard exists before the first demotion ever lands —
                # dlt_kv_tier_hits_total{tier=host|disk|peer} (+ misses)
                counter_series["kv_tier_hits"] = [
                    ({"tier": t}, snap_counters.get(f"kv_tier_hits_{t}", 0))
                    for t in ("host", "disk", "peer")
                ]
                counter_series["kv_tier_demotions"] = [
                    ({"tier": t}, snap_counters.get(f"kv_tier_demoted_{t}", 0))
                    for t in ("host", "disk")
                ]
            if st.batcher is not None:
                # scheduler decisions by (class, action) — zero-filled so
                # the preemption dashboard exists before the first incident
                counter_series["scheduler_decisions"] = (
                    st.batcher.scheduler.decisions_series()
                )
            # supervisor lifecycle transitions by state (zero-filled):
            # dlt_supervisor_transitions_total{state=serving|recovering|
            # failed} — a recovering spike IS the incident timeline
            counter_series["supervisor_transitions"] = (
                st.supervisor.transitions_series()
            )
            body = render_step_stats(
                st.engine.stats, extra_gauges=extra, extra_series=series,
                extra_counter_series=counter_series,
            )
            self._respond(200, body.encode(), ctype=PROM_CONTENT_TYPE)
            return
        if route == "/debug/costs":
            # the warm-ladder cost table (runtime/profiling.py): builds
            # lazily on first hit (AOT compile work — a cold operator
            # action, never a serving-path cost; the engine runs it inside
            # the sentinel's thread-scoped exempt() window so fatal-
            # sanitizer servers stay clean while serving threads keep full
            # breach detection). Coverage vs warm_plan() rides the payload — the same
            # contract `graph_audit --costs` enforces.
            engine = self.state.engine
            table = engine.cost_table()
            body = json.dumps(table.snapshot(engine.warm_plan())).encode()
            self._json(200, body)
            return
        if route == "/debug/profile":
            from ..runtime.profiling import ProfileBusy, capture_profile

            try:
                ms = int(self._query_params().get("ms", "500"))
            except ValueError:
                self._json(400, b'{"error":"bad ms parameter"}')
                return
            try:
                rec = capture_profile(ms)
            except ProfileBusy:
                self._json(
                    409, b'{"error":"a profile capture is already in flight"}'
                )
                return
            except Exception as e:
                self._json(
                    500,
                    json.dumps({"error": f"profiler failed: {e}"}).encode(),
                )
                return
            self._json(200, json.dumps(rec).encode())
            return
        if route == "/debug/trace":
            tid = self._query_params().get("id", "")
            events = TRACER.for_trace(tid) if tid else []
            if not events:
                self._json(404, b'{"error":"unknown or expired trace id"}')
                return
            self._json(200, json.dumps(trace_payload(tid, events)).encode())
            return
        if route == "/debug/batch_timeline":
            # batch-composition timeline (runtime/tracing.py): the sampled
            # per-step slot snapshots + park/shed marks still in the ring,
            # as JSON events and a chrome://tracing export — the post-hoc
            # view of admission stalls, park livelocks, and pool thrash
            events = TRACER.for_names(BATCH_TIMELINE_NAMES)
            self._json(200, json.dumps(batch_timeline_payload(events)).encode())
            return
        if route == "/debug/hot_prefixes":
            # warm drain handoff (server/scheduler.py HotPrefixTracker +
            # server/autoscaler.py): this replica's hottest router chain
            # keys — the gateway fetches this snapshot before draining the
            # replica and re-homes the listed chains' affinity so
            # shared-prefix traffic re-concentrates instead of spraying
            from .router import PAGE_CHARS

            try:
                top_n = int(self._query_params().get("n", "64"))
            except ValueError:
                top_n = 64
            snap = self.state.hot_prefixes.snapshot(top_n=max(1, top_n))
            snap["block_chars"] = PAGE_CHARS
            self._json(200, json.dumps(snap).encode())
            return
        if route == "/debug/quarantine":
            # crash-only gateway recovery (server/recovery.py): the FULL
            # fresh strike ledger with per-entry ages — a warm-restarting
            # gateway re-learns strikes (and in-force 422s) from every
            # replica, so a gateway crash never refreshes a poison body's
            # replica-killing budget
            self._json(200, json.dumps(self.state.quarantine.dump()).encode())
            return
        if route == "/debug/config":
            self._json(200, json.dumps(resolved_config(self.state)).encode())
            return
        if route == "/debug/flightrecord":
            rec = last_flight_record()
            if rec is None:
                self._json(404, b'{"error":"no flight record yet"}')
                return
            self._json(200, json.dumps(rec).encode())
            return
        if self.path == "/v1/models":
            body = json.dumps(
                {
                    "object": "list",
                    "data": [
                        {"id": MODEL_NAME, "object": "model", "created": 0, "owned_by": "user"}
                    ],
                }
            ).encode()
            self._json(200, body)
        elif self.path == "/health":
            # the gateway's active prober reads this: status plus the same
            # robustness counters /stats exports (StepStats counters), so
            # the two views can never disagree about what the engine saw.
            # A replica mid-rebuild (or out of restart budget) answers 503
            # with its supervisor state — the prober opens the breaker and
            # the fleet routes away until the rebuild rejoins; the
            # quarantine's implicated fingerprints ride along so the
            # gateway (and dashboards) can attribute WHY it went down.
            st = self.state
            sup = st.supervisor.snapshot()
            payload = {
                "status": "ok" if sup["state"] == "serving" else sup["state"],
                "counters": st.engine.stats.counters_snapshot(),
                "queue_depth": st.batcher.queue_depth() if st.batcher is not None else 0,
                "supervisor": sup,
                "quarantine": st.quarantine.snapshot(),
                # the drain hint the draining gateway posted — the warm
                # -restart recovery source for draining flags + autoscaler
                # drain ownership (server/recovery.py)
                "draining": st.draining_hint,
            }
            code = 200 if sup["state"] == "serving" else 503
            self._json(code, json.dumps(payload).encode())
        elif self.path == "/stats":
            # operator view of the serving loop (the reference prints its
            # network perf report only at shutdown, nn-network.cpp:883-1053;
            # this surfaces the same numbers live, plus Batcher occupancy)
            st = self.state
            pc = st.engine.prefix_cache
            from ..runtime.speculative import spec_snapshot

            payload = {
                "steps": st.engine.stats.snapshot(),
                "batcher": st.batcher.stats() if st.batcher is not None else None,
                # prefix-cache occupancy; the hit/eviction counters
                # (prefix_hits, prefix_hit_tokens, prefix_evictions, ...)
                # ride steps.counters like every other engine event
                "prefix_cache": pc.stats_snapshot() if pc is not None else None,
                # speculative decoding config + acceptance counters (the
                # spec_* counters ride steps.counters and /health too; this
                # section is the one-stop operator view)
                "speculative": spec_snapshot(st.engine),
                # structured output (runtime/grammar.py): arena occupancy
                # + compile-cache counters (None = unconstrained replica)
                "grammar": (
                    None if st.engine.grammar is None else dict(
                        st.engine.grammar.snapshot(),
                        compiler=(
                            st.grammar_compiler.cache_stats()
                            if st.grammar_compiler is not None
                            else None
                        ),
                    )
                ),
                # paged KV pool occupancy (None on contiguous engines); the
                # kv_cow_* / kv_pages_shared / kv_pool_* counters ride
                # steps.counters like every other engine event
                "kv_pool": (
                    dict(
                        st.engine.page_pool.snapshot(),
                        layout=st.engine.kv_layout,
                    )
                    if st.engine.paged
                    else None
                ),
                # per-request goodput rollup: outcomes, delivered vs wasted
                # tokens (by reason), recent-window delivered-token rate —
                # incl. the by_class breakdown (server/scheduler.py)
                "goodput": st.goodput.snapshot(),
                # SLO-class scheduler policy + (class, action) decision
                # counts (server/scheduler.py; None on serialized servers)
                "scheduler": (
                    st.batcher.scheduler.snapshot()
                    if st.batcher is not None
                    else None
                ),
                # disaggregated serving (server/disagg.py): this replica's
                # role and, on decode workers, the prefill-peer view — the
                # disagg_* counters ride steps.counters like every other
                # engine event; the fleet scraper lifts both into the
                # per-replica table
                "role": st.role,
                "disagg": None if st.disagg is None else st.disagg.snapshot(),
                # tiered KV store (runtime/kv_tiering.py): per-tier
                # occupancy/budgets + fleet-cache peer health — the
                # kv_tier_* counters ride steps.counters; the fleet
                # scraper lifts this section into the per-replica table
                "kv_tiering": (
                    None if st.kv_tier is None else st.kv_tier.snapshot()
                ),
                # supervised engine lifecycle (runtime/supervisor.py):
                # state, restart budget, transition counts — the /metrics
                # twin is dlt_supervisor_transitions_total{state=...}
                "supervisor": st.supervisor.snapshot(),
                # poison-request quarantine (server/quarantine.py):
                # implicated fingerprints + strike counts
                "quarantine": st.quarantine.snapshot(),
                "model": MODEL_NAME,
                "batch": st.engine.batch,
                "seq_len": st.engine.cfg.seq_len,
            }
            self._json(200, json.dumps(payload).encode())
        else:
            self._json(404, b'{"error":"not found"}')

    def do_POST(self):
        if self.path == "/v1/prefill":
            self._serve_prefill()
            return
        if self.path == "/v1/kv_fetch":
            self._serve_kv_fetch()
            return
        if self.path == "/admin/drain_hint":
            # the gateway's crash-safety hint (Balancer.set_draining):
            # remember the drain (and its actuator) so a warm-restarting
            # gateway reads it back from /health (server/recovery.py).
            # Advisory only — this replica keeps serving whatever arrives;
            # the gateway owns the actual stop-new-assignments decision.
            length = int(self.headers.get("Content-Length", 0))
            try:
                hint = json.loads(self.rfile.read(length) or b"{}")
                draining = bool(hint.get("draining"))
                by = str(hint.get("by", "operator"))
            except (ValueError, AttributeError):
                self._json(400, b'{"error":"bad json"}')
                return
            self.state.draining_hint = (
                {"draining": True, "by": by} if draining else None
            )
            self._json(200, json.dumps(
                {"draining": self.state.draining_hint}
            ).encode())
            return
        if self.path != "/v1/chat/completions":
            self._json(404, b'{"error":"not found"}')
            return
        if self.state.role == "prefill":
            # a prefill worker owns its chips for prompt compute; routing
            # chat here is a topology error, not something to half-serve
            self._json(
                404, b'{"error":"this replica serves role=prefill; '
                b'POST /v1/prefill"}'
            )
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            params = json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError:
            self._json(400, b'{"error":"bad json"}')
            return
        if "messages" not in params:
            self._json(400, b'{"error":"messages required"}')
            return
        # SLO class (server/scheduler.py): the X-DLT-SLO-Class header (the
        # gateway forwards client headers byte-transparently, retries
        # included) wins over the body's slo_class; unknown values degrade
        # to standard. Normalized ONCE here so every downstream reader
        # (Batcher, ledgers, scheduler counters) sees one canonical value.
        params["slo_class"] = resolve_slo_class(
            self.headers.get(SLO_CLASS_HEADER) or params.get("slo_class")
        )
        # warm-handoff tracker: this request's router-compatible prefix
        # chain keys (None for garbage message shapes — the 400 below owns
        # those; one bounded-dict touch per request, never per token)
        from .router import PREFETCH_CHAIN_HEADER, messages_prefix_text, \
            parse_chain_header, prefix_chain

        prefix_text = messages_prefix_text(params.get("messages"))
        if prefix_text:
            chain = prefix_chain(prefix_text)
            self.state.hot_prefixes.record(chain)
            # stash for the completion path: the tiered store's prefetch-
            # hint index maps these router chain keys to the token prefix
            # they resolve to (runtime/kv_tiering.py note_chain), and the
            # hot-prefix tracker gets the tokenized footprint (note_size)
            params["_chain"] = chain
        # router prefetch hint (X-DLT-Prefetch-Chain): the gateway names
        # the chain it EXPECTS here next, so the tiered store can lift the
        # matching prefix disk/peer -> host before the request lands.
        # Best-effort and bounded; garbage headers are ignored.
        if self.state.kv_tier is not None:
            hinted = parse_chain_header(
                self.headers.get(PREFETCH_CHAIN_HEADER)
            )
            if hinted:
                self.state.kv_tier.prefetch_hint(hinted)

        # poison-request quarantine (server/quarantine.py): fingerprint the
        # FULL conversation; a fingerprint already implicated in `limit`
        # engine failures is refused with a terminal 422 BEFORE it can
        # touch the engine — one bad request must never take this replica
        # down twice, however many times the client (or a misconfigured
        # gateway) replays it
        self._poison_fp = request_fingerprint(prefix_text)
        if self.state.quarantine.is_quarantined(self._poison_fp):
            self.state.engine.stats.incr("quarantined_422")
            # prompt-token estimate (~4 chars/token, the router's own
            # approximation): the refused request's parse/route work is
            # `quarantined` waste — the signal the acceptance bar reads
            self.state.goodput.add_waste(
                "quarantined", max(len(prefix_text or "") // 4, 1),
                params["slo_class"],
            )
            self._json(
                422, json.dumps({
                    "error": "request quarantined: this conversation has "
                    "repeatedly crashed or stalled the engine",
                    "fingerprint": fp_hex(self._poison_fp),
                }).encode(),
                headers={POISON_HEADER: fp_hex(self._poison_fp)},
            )
            return

        # end-to-end deadline (server/scheduler.py): the gateway mints
        # X-DLT-Deadline-Ms (re-stamped with the REMAINING budget on every
        # retry) or a direct client sends it; resolved once here to a
        # monotonic instant every downstream check compares against
        deadline_ms = resolve_deadline_ms(
            params["slo_class"], self.headers.get(DEADLINE_HEADER)
        )
        if deadline_ms > 0:
            params["_deadline"] = time.monotonic() + deadline_ms / 1e3

        # request-lifecycle trace: adopt the gateway's X-DLT-Trace-Id (one
        # joinable identity across gateway -> retry -> backend) — and its
        # X-DLT-Trace-Sampled decision, so the 1-in-N trace the gateway
        # chose to keep gets its backend detail spans too — or mint one
        # for direct traffic; every response echoes it (_json/start_stream)
        tr = TRACER.start(
            self.headers.get(TRACE_HEADER),
            sampled=parse_sampled(self.headers.get(SAMPLED_HEADER)),
        )
        self._trace = tr
        t_req0 = now_us()

        stream = bool(params.get("stream", False))
        try:
            self._serve_chat(params, stream)
        finally:
            # terminal request span: always recorded (one event/request) so
            # /debug/trace reconstructs even unsampled or failed requests
            tr.event(
                "request", t_req0, now_us() - t_req0, ("path", "status"),
                (self.path, getattr(self, "_last_status", 200)), always=True,
            )

    def _serve_prefill(self):
        """``POST /v1/prefill`` (server/disagg.py): prefill workers run the
        prompt's leading bucket and ship the extracted KV as one binary
        payload. Other roles 404 — the decode worker's degradation path
        treats that exactly like a dead peer."""
        st = self.state
        if st.role != "prefill":
            self._json(
                404, b'{"error":"this replica does not serve role=prefill"}'
            )
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            params = json.loads(self.rfile.read(length) or b"{}")
            ids = [int(t) for t in params["ids"]]
            # content-addressed skip claim (runtime/kv_transport.py): the
            # requester's chained page-key names for the leading pages it
            # already holds — hex strings on the wire. A malformed claim
            # degrades to a full send, never an error.
            try:
                have = tuple(int(h, 16) for h in params.get("have", ()))
            except (TypeError, ValueError):
                have = ()
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            self._json(400, b'{"error":"ids (a token id list) required"}')
            return
        if not ids:
            self._json(400, b'{"error":"empty ids"}')
            return
        # adopt the decode worker's trace id so one trace stitches
        # decode-worker -> kv_transfer -> prefill-worker spans together
        tr = TRACER.start(
            self.headers.get(TRACE_HEADER),
            sampled=parse_sampled(self.headers.get(SAMPLED_HEADER)),
        )
        self._trace = tr
        t0 = now_us()
        from .disagg import run_prefill

        try:
            payload = run_prefill(st, ids, have=have, trace=tr)
        except ValueError as e:
            self._json(400, json.dumps({"error": str(e)}).encode())
            return
        except Exception as e:
            # engine failure: recover like the chat path (supervised reset/
            # rebuild + prefix cache drop) and report — the decode worker
            # degrades locally either way
            st.recover(exc=e)
            self._json(
                500, json.dumps({"error": f"prefill failed: {e}"}).encode()
            )
            return
        finally:
            tr.event(
                "prefill_request", t0, now_us() - t0, ("n_ids",), (len(ids),),
                always=True,
            )
        self._respond(200, payload, ctype="application/octet-stream")

    def _serve_kv_fetch(self):
        """``POST /v1/kv_fetch`` (runtime/kv_tiering.py): fleet-cache tier.
        A peer replica names a token prefix (plus a content-addressed skip
        claim for pages it already holds) and gets back one verified binary
        KV payload from this replica's tiered store — or a 404 the requester
        treats exactly like a miss. Serving never touches the device: only
        host/disk tiers answer, so a busy decode loop is never stalled by a
        peer's cache fill."""
        st = self.state
        if st.kv_tier is None:
            self._json(404, b'{"error":"kv tiering disabled"}')
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            params = json.loads(self.rfile.read(length) or b"{}")
            ids = [int(t) for t in params["ids"]]
            # malformed skip claims degrade to a full send, never an error
            # (same contract as /v1/prefill)
            try:
                have = tuple(int(h, 16) for h in params.get("have", ()))
            except (TypeError, ValueError):
                have = ()
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            self._json(400, b'{"error":"ids (a token id list) required"}')
            return
        if not ids:
            self._json(400, b'{"error":"empty ids"}')
            return
        payload = st.kv_tier.serve_fetch(ids, have_keys=have)
        if payload is None:
            self._json(404, b'{"error":"miss"}')
            return
        self._respond(200, payload, ctype="application/octet-stream")

    def _serve_chat(self, params, stream):
        st = self.state
        tr = self._trace
        # batch mode: the Batcher serializes engine access and groups
        # concurrent requests into one generation — no global lock, so
        # handler threads can actually arrive concurrently
        if st.batcher is not None:
            complete_fn = st.complete_batched
            lock_ctx = contextlib.nullcontext()
        else:
            complete_fn = st.complete
            lock_ctx = st.lock
        with lock_ctx:
            if stream:
                # headers go out lazily on the first emitted chunk, so a
                # validation failure (e.g. prompt too long) can still return
                # a clean 400 instead of a broken SSE stream
                started = [False]

                def start_stream():
                    if not started[0]:
                        self.send_response(200)
                        self.send_header("Content-Type", "text/event-stream")
                        self.send_header("Connection", "close")
                        if tr is not None:
                            self.send_header(TRACE_HEADER, tr.id)
                        self.end_headers()
                        started[0] = True

                def emit(delta):
                    try:
                        start_stream()
                        data = json.dumps(chunk_json(delta, False))
                        self.wfile.write(f"data: {data}\r\n\r\n".encode())
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionError) as e:
                        # tag socket failures at the emit site so complete()
                        # can tell a client drop from an engine failure
                        raise ClientDisconnected(str(e)) from e

                try:
                    text, n_prompt, n_completion, _led = complete_fn(
                        params, emit, trace=tr
                    )
                except PromptTooLong as e:
                    if not started[0]:
                        self._json(400, json.dumps({"error": str(e)}).encode())
                        return
                    raise
                except GrammarError as e:
                    # malformed/unsupported response_format: a client 400
                    # raised before the first SSE byte — and crucially
                    # BEFORE the generic arm below, so a grammar bomb never
                    # lands a poison strike on its conversation fingerprint
                    if not started[0]:
                        self._json(400, json.dumps({"error": str(e)}).encode())
                        return
                    raise
                except Overloaded as e:
                    # shed BEFORE any SSE byte goes out (the backlog check
                    # runs ahead of the first emit), so the 503 is clean
                    if not started[0]:
                        self._json(
                            503, b'{"error":"server overloaded"}',
                            headers={"Retry-After": str(e.retry_after_s)},
                        )
                        return
                    raise
                except ClientDisconnected:
                    return  # nothing to send — the socket is gone
                except DeadlineExceeded as e:
                    # deadline passed before the first SSE byte: a clean
                    # 504; mid-stream the truncation IS the signal
                    if not started[0]:
                        self._json(
                            504, json.dumps({"error": str(e)}).encode()
                        )
                        return
                    raise
                except Exception as e:
                    # engine failure before any SSE chunk went out: return a
                    # clean 500 like the non-stream path (the implicated
                    # fingerprint rides the response — quarantine
                    # attribution); mid-stream the only honest signal left
                    # is EOF, but the strike still lands
                    hdrs = self._poison_strike()
                    if not started[0]:
                        self._json(
                            500,
                            json.dumps({"error": f"engine error: {e}"}).encode(),
                            headers=hdrs,
                        )
                        return
                    raise
                start_stream()
                data = json.dumps(chunk_json(None, True))
                self.wfile.write(f"data: {data}\r\n\r\n".encode())
                self.wfile.write(b"data: [DONE]")
                self.close_connection = True
            else:
                try:
                    # non-stream: emit is a no-op and the response is built
                    # from the return value only — a stall retry can never
                    # duplicate client-visible bytes
                    text, n_prompt, n_completion, led = complete_fn(
                        params, lambda d: None, client_visible=False, trace=tr
                    )
                except PromptTooLong as e:
                    self._json(400, json.dumps({"error": str(e)}).encode())
                    return
                except GrammarError as e:
                    # client-input 400, ahead of the poison-strike arm: a
                    # malformed response_format must never strike its
                    # conversation's fingerprint
                    self._json(400, json.dumps({"error": str(e)}).encode())
                    return
                except Overloaded as e:
                    self._json(
                        503, b'{"error":"server overloaded"}',
                        headers={"Retry-After": str(e.retry_after_s)},
                    )
                    return
                except DeadlineExceeded as e:
                    self._json(504, json.dumps({"error": str(e)}).encode())
                    return
                except Exception as e:  # engine failure: recovered by
                    # complete(); report it instead of dropping the socket
                    # — with the implicated fingerprint riding the 500
                    self._json(
                        500, json.dumps({"error": f"engine error: {e}"}).encode(),
                        headers=self._poison_strike(),
                    )
                    return
                body = json.dumps(
                    {
                        "id": "cmpl-j0",
                        "object": "chat.completion",
                        "created": 0,
                        "model": MODEL_NAME,
                        "usage": {
                            "prompt_tokens": n_prompt,
                            "completion_tokens": n_completion,
                            "total_tokens": n_prompt + n_completion,
                            # goodput-ledger extension: where this request's
                            # wall time went and what every decoded token
                            # became (runtime/telemetry.py GoodputLedger) —
                            # standard OpenAI clients ignore unknown usage
                            # keys; fleet tooling joins on them
                            "goodput": led.as_dict() if led is not None else None,
                        },
                        "choices": [
                            {
                                "index": 0,
                                "message": {"role": "assistant", "content": text},
                                "finish_reason": "",
                            }
                        ],
                    }
                ).encode()
                self._json(200, body)

    def send_response(self, code, message=None):
        self._last_status = code  # the terminal request span reads this
        super().send_response(code, message)

    def _respond(
        self, code: int, body: bytes,
        ctype: str = "application/json; charset=utf-8",
        headers: dict | None = None,
    ):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if self._trace is not None:
            # echo the request's trace id on every response, so a client
            # (or the gateway in front) can join its logs to /debug/trace
            self.send_header(TRACE_HEADER, self._trace.id)
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        # close after every response (reference: dllama-api.cpp:202-235):
        # the server handles one connection at a time, so a pooled keep-alive
        # client would otherwise wedge it for everyone else
        self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(body)
        self.close_connection = True

    def _json(self, code: int, body: bytes, headers: dict | None = None):
        self._respond(code, body, headers=headers)


def serve(args) -> HTTPServer:
    """Build state and return a configured (unstarted) HTTPServer.

    batch == 1: single-threaded server, serialized requests + prefix cache
    (the reference's model). batch > 1: threaded server so concurrent
    handlers can reach the Batcher together."""
    from http.server import ThreadingHTTPServer

    from ..cli import make_engine

    # since the KV movement layer (runtime/kv_transport.py), BOTH serving
    # roles speak both KV layouts: paged workers extract/insert through the
    # warmed page_extract/page_insert programs, so the old roles-force-
    # contiguous override is gone and the paged default applies everywhere
    engine = make_engine(args)
    tokenizer = Tokenizer(args.tokenizer)
    import os as _os

    if not _os.environ.get("DLT_NO_WARMUP"):
        # compile the chunk ladder before accepting connections so the first
        # request pays serving latency, not XLA compile (cold-TTFT)
        engine.warmup()
        if _os.environ.get("DLT_COST_TABLE") != "0":
            # serving processes carry the warm-ladder cost table from the
            # start (/debug/costs, /metrics roofline gauges); with
            # DLT_COMPILE_CACHE set the AOT compiles dedupe against the
            # warmup the line above just paid. DLT_COST_TABLE=0 opts out
            # (e.g. slow remote-compiler tunnels); the table then builds
            # lazily on the first /debug/costs hit.
            engine.cost_table()
    state = ApiState(engine, tokenizer, args)
    # same-process device-path registry (runtime/kv_transport.py): a decode
    # worker whose --prefill-peer names this port reaches the prefill
    # engine as device arrays, no socket — DLT_KV_TRANSPORT governs whether
    # clients actually take it (auto: device whenever registered)
    from ..runtime.kv_transport import register_device_peer

    register_device_peer(args.port, state)
    # a fresh Handler subclass per server: `state` as a class attribute on
    # the shared Handler would make two in-process replicas (gateway tests,
    # library embedders) clobber each other's engines. Handler.state stays
    # assigned for the single-server common case and back-compat.
    handler_cls = type("Handler", (Handler,), {"state": state})
    Handler.state = state
    cls = ThreadingHTTPServer if state.batcher is not None else HTTPServer

    class _ApiServer(cls):
        # engine lifetime rides the server's: shutdown()/server_close()
        # also stop the Batcher loop and close the engine — which
        # unsubscribes its recompile sentinel. Without this, every
        # torn-down server leaked its engine on the Batcher's daemon
        # thread, and a leaked SEALED fatal sentinel killed every later
        # engine build in the process (the cross-suite pollution class).
        api_state = state

        def shutdown(self):
            super().shutdown()
            self.api_state.close()

        def server_close(self):
            super().server_close()
            self.api_state.close()

    return _ApiServer(("0.0.0.0", args.port), handler_cls)


def main(argv=None) -> int:
    import time

    from ..cli import build_arg_parser

    p = build_arg_parser()
    p.add_argument("--port", type=int, default=9990)
    p.add_argument(
        "--restart-delay", type=float, default=3.0,
        help="seconds between automatic server restarts after a crash; "
        "<0 disables the restart loop",
    )
    # mode positional comes from the shared parser; default it away
    argv = ["inference"] + (argv if argv is not None else __import__("sys").argv[1:])
    args = p.parse_args(argv)
    if args.model is None or args.tokenizer is None:
        p.error("--model and --tokenizer are required")
    # auto-restart outer loop (reference: dllama-api.cpp:624-636 rebuilds the
    # whole server every 3 s after a crash). Per-request engine failures are
    # already absorbed by ApiState.recover() + a 500 response; this loop is
    # the last-resort layer for accept-loop/socket-level crashes that escape
    # serve_forever. Only restart once the server came up at least once — a
    # config error at startup (bad model path, tokenizer without a chat
    # template) is permanent and must fail loudly, not loop.
    ever_started = False
    while True:
        httpd = None
        try:
            httpd = serve(args)
            print(f"🚧 Listening on port {args.port}...")
            ever_started = True
            httpd.serve_forever()
            return 0
        except KeyboardInterrupt:
            return 0
        except Exception as e:
            if args.restart_delay < 0 or not ever_started:
                raise
            print(f"💥 server crashed: {e!r}; restarting in {args.restart_delay}s")
            time.sleep(args.restart_delay)
        finally:
            if httpd is not None:
                # release the listening socket — rebinding over a live
                # listener fails with EADDRINUSE even with SO_REUSEADDR
                httpd.server_close()


if __name__ == "__main__":
    raise SystemExit(main())
