"""Crash-only gateway recovery: rebuild control-plane state from the fleet.

A gateway restart used to be a silent control-plane wipe: the signal
table re-filled only after a scrape interval, the router's learned
prefix-locality map came back EMPTY (every shared-prefix request
re-cold-prefilled somewhere — the PR 10 routing leg's ~3x TTFT gap,
re-paid on every deploy), the quarantine ledger forgot every in-force
422 (a poison body got a fresh replica-killing budget), and replicas the
autoscaler had drained were stranded — draining flags live on the
gateway, so a fresh gateway neither knew about the drain nor owned it.

The fix is the crash-only discipline the replica tier got in PR 14: the
authoritative state never lived only in the gateway — the FLEET holds
it, and startup reads it back before the first client request:

* **signal table** — one synchronous :meth:`FleetScraper.scrape_once`
  sweep primes every replica's row (rate fields need a SECOND scrape for
  a baseline; the router's scoring degrades to headroom/affinity for
  that one interval — see ``score_backend``, which never reads rates);
* **locality map** — every replica's ``GET /debug/hot_prefixes`` (the
  PR 12 warm-handoff surface, reused verbatim) is merged: each chain key
  re-homes to the replica that reports it HOTTEST, rendezvous-hashing
  breaking ties, then bulk-loaded via ``Router.prime_locality``;
* **quarantine ledger** — every replica's ``GET /debug/quarantine``
  dump is merged: strikes SUM across replicas (each incident burned one
  replica, so the fleet-wide count is the sum) with TTL-correct ages, so
  in-force 422s stay in force across the restart;
* **drain state** — every replica's ``GET /health`` carries the drain
  hint the draining gateway posted (``POST /admin/drain_hint``):
  ``draining`` flags are restored, and hints stamped ``by=autoscaler``
  re-enter the autoscaler's ``_drained_by_me`` ownership so the control
  loop can still undrain what it drained.

Everything is best-effort and bounded (one thread per backend, one
timeout): a dead replica contributes nothing, a half-answering one
contributes what it has, and the result counters land on ``/metrics``
as the ``dlt_gateway_recovery_*`` family plus a ``recovery`` section in
``GET /gateway/fleet``. Stdlib-only, like the rest of the gateway.
"""

from __future__ import annotations

import json
import os
import threading
import time

from ..runtime.tracing import TRACER, now_us
from .quarantine import parse_fp_hex
from .router import rendezvous_owner

#: how many hot chains to ask each replica for (the autoscaler's warm
#: handoff asks for 64; recovery rebuilds the WHOLE map, so it asks for
#: more — still one bounded response per replica)
RECOVERY_HOT_N = 512

#: per-GET timeout AND the overall recovery budget anchor: every backend
#: is swept in its own thread and the join is bounded at timeout + 0.5 s,
#: so a fleet of black-holing sockets delays serving by ~this much, not
#: backends x surfaces x timeout (the gateway must come up promptly even
#: when the whole fleet is hung — it will shed honestly, not hang)
DEFAULT_RECOVER_TIMEOUT_S = 2.0


def _recover_timeout_s() -> float:
    try:
        return float(os.environ.get(
            "DLT_GW_RECOVER_TIMEOUT_S", DEFAULT_RECOVER_TIMEOUT_S
        ))
    except ValueError:
        return DEFAULT_RECOVER_TIMEOUT_S


def _fetch_backend_state(host: str, port: int, timeout_s: float) -> dict:
    """One backend's recovery sources, best-effort: ``{"health": ...,
    "hot_prefixes": ..., "quarantine": ...}`` with None for any surface
    that failed (older replicas without /debug/quarantine just miss it)."""
    from .fleet import http_get_text

    out = {"health": None, "hot_prefixes": None, "quarantine": None}
    for key, path, ok_codes in (
        # a recovering replica answers /health 503 WITH its payload —
        # drain hints must survive a concurrent engine rebuild
        ("health", "/health", (200, 503)),
        ("hot_prefixes", f"/debug/hot_prefixes?n={RECOVERY_HOT_N}", (200,)),
        ("quarantine", "/debug/quarantine", (200,)),
    ):
        try:
            status, body = http_get_text(host, port, path, timeout_s)
            if status in ok_codes:
                payload = json.loads(body)
                if isinstance(payload, dict):
                    out[key] = payload
        except Exception:
            pass  # dlt: allow(swallowed-exception) — recovery is
            # best-effort by contract: a dead/garbled replica contributes
            # nothing and is counted in replicas_failed by the caller
    return out


def merge_hot_prefixes(per_backend: dict) -> dict:
    """``{chain_key_int: backend_key}`` from per-replica hot-prefix
    snapshots: each chain key goes to the replica reporting it HOTTEST
    (its cache most certainly holds it); ties rendezvous-hash over the
    tied replicas so every recovering gateway picks the SAME home."""
    best: dict = {}  # ck -> (hits, [backend_keys])
    for backend_key, snap in per_backend.items():
        for ent in (snap or {}).get("chains") or []:
            try:
                ck = int(ent["key"], 16)
                hits = int(ent.get("hits", 1))
            except (TypeError, ValueError, KeyError):
                continue
            cur = best.get(ck)
            if cur is None or hits > cur[0]:
                best[ck] = (hits, [backend_key])
            elif hits == cur[0]:
                cur[1].append(backend_key)
    owners = {}
    for ck, (_, keys) in best.items():
        owners[ck] = keys[0] if len(keys) == 1 else rendezvous_owner(ck, keys)
    return owners


def merge_quarantine(per_backend: dict) -> dict:
    """``{fp_int: (strikes, min_age_s)}`` summed across replicas: each
    strike was one incident on one replica, so the fleet-wide count is
    the sum; the youngest age keeps the TTL honest (the entry lives as
    long as its most recent incident would have)."""
    merged: dict = {}
    for snap in per_backend.values():
        for ent in (snap or {}).get("entries") or []:
            fp = parse_fp_hex(ent.get("fp"))
            if fp is None:
                continue
            try:
                strikes = int(ent.get("strikes", 0))
                age = float(ent.get("age_s", 0.0))
            except (TypeError, ValueError):
                continue
            if strikes <= 0:
                continue
            cur = merged.get(fp)
            merged[fp] = (
                (strikes, age) if cur is None
                else (cur[0] + strikes, min(cur[1], age))
            )
    return merged


def recover_gateway(balancer, timeout_s: float | None = None) -> dict:
    """The warm-restart sweep. Returns (and the caller publishes) the
    recovery record; never raises — a fleet that answers nothing yields a
    cold start, exactly the pre-recovery behavior."""
    t0 = time.monotonic()
    fleet = getattr(balancer, "fleet", None)
    if timeout_s is None:
        timeout_s = _recover_timeout_s()
    # ONE bounded worker per backend does everything for that backend —
    # the synchronous scrape prime (the first routed request must score
    # against a populated table, not a never-scraped one) AND the three
    # recovery fetches. The join is bounded by the recovery budget, so a
    # fleet of hung sockets delays serving by ~timeout_s, never
    # backends x surfaces x timeout; a worker finishing late still lands
    # its scrape in the fleet table (the scraper owns that state), it
    # just misses this recovery record.
    backends = list(balancer.config.backends)
    raw: dict = {}

    def fetch(b):
        if fleet is not None:
            try:
                fleet._scrape_backend(b)
            except Exception:
                pass  # dlt: allow(swallowed-exception) — the scraper's
                # own contract is never-raise; this is belt over it so a
                # scrape bug cannot void the rest of the recovery sweep
        raw[b.key] = _fetch_backend_state(b.host, b.port, timeout_s)

    threads = [
        threading.Thread(target=fetch, args=(b,), daemon=True)
        for b in backends
    ]
    for t in threads:
        t.start()
    deadline = time.monotonic() + timeout_s + 0.5
    for t in threads:
        t.join(timeout=max(deadline - time.monotonic(), 0.05))
    answered = {
        k: v for k, v in raw.items()
        if any(v[s] is not None for s in v)
    }
    # 3) locality: hottest-reporter wins, rendezvous ties
    owners = merge_hot_prefixes(
        {k: v["hot_prefixes"] for k, v in answered.items()}
    )
    router = getattr(balancer, "router", None)
    locality_keys = 0
    if router is not None and owners:
        locality_keys = router.prime_locality(owners)
    # 4) quarantine: summed strikes, TTL-correct ages
    merged = merge_quarantine(
        {k: v["quarantine"] for k, v in answered.items()}
    )
    ledger = getattr(balancer, "quarantine", None)
    quarantine_fps = in_force = 0
    if ledger is not None:
        for fp, (strikes, age) in merged.items():
            ledger.prime(fp, strikes, age)
            quarantine_fps += 1
            if ledger.is_quarantined(fp):
                in_force += 1
    # 5) drain state: restore flags + autoscaler ownership from the
    # replicas' drain hints (record=False: a restored drain is not a new
    # event to gossip as ours with a fresh clock — peers that saw the
    # original still hold it; notify=False: the replica ALREADY carries
    # the hint we just read)
    drains_restored = drains_adopted = 0
    autoscaler = getattr(balancer, "autoscaler", None)
    for key, v in answered.items():
        hint = (v["health"] or {}).get("draining")
        if not isinstance(hint, dict) or not hint.get("draining"):
            continue
        by = str(hint.get("by", "operator"))
        if balancer.set_draining(key, True, by=by, record=False, notify=False):
            drains_restored += 1
            if by == "autoscaler" and autoscaler is not None:
                autoscaler.adopt_drain(key)
                drains_adopted += 1
    record = {
        "runs": 1,
        "replicas_polled": len(backends),
        "replicas_answered": len(answered),
        "replicas_failed": len(backends) - len(answered),
        "locality_keys": locality_keys,
        "quarantine_fps": quarantine_fps,
        "quarantine_in_force": in_force,
        "drains_restored": drains_restored,
        "drains_adopted": drains_adopted,
        "wall_ms": round((time.monotonic() - t0) * 1e3, 1),
    }
    TRACER.event(
        "gw_recovery", now_us(), int(record["wall_ms"] * 1e3),
        ("answered", "locality_keys", "quarantine_fps", "drains_restored"),
        (record["replicas_answered"], locality_keys, quarantine_fps,
         drains_restored),
    )
    return record


def recovery_metrics_lines(record: dict | None) -> list:
    """``dlt_gateway_recovery_*`` exposition — zero-filled when recovery
    was disabled, so dashboards can tell "recovered nothing" from "never
    ran" via dlt_gateway_recovery_runs_total."""
    from ..runtime.tracing import prom_line

    rec = record or {}
    lines = []
    for name, key, kind in (
        ("dlt_gateway_recovery_runs_total", "runs", "counter"),
        ("dlt_gateway_recovery_replicas_answered", "replicas_answered",
         "gauge"),
        ("dlt_gateway_recovery_replicas_failed", "replicas_failed", "gauge"),
        ("dlt_gateway_recovery_locality_keys_total", "locality_keys",
         "counter"),
        ("dlt_gateway_recovery_quarantine_fps_total", "quarantine_fps",
         "counter"),
        ("dlt_gateway_recovery_quarantine_in_force", "quarantine_in_force",
         "gauge"),
        ("dlt_gateway_recovery_drains_restored_total", "drains_restored",
         "counter"),
        ("dlt_gateway_recovery_wall_ms", "wall_ms", "gauge"),
    ):
        lines.append(f"# TYPE {name} {kind}")
        lines.append(prom_line(name, None, rec.get(key, 0)))
    return lines
