"""Poison-request quarantine: one bad request must never take down a fleet.

The gateway's zero-byte transparent retry (PR 1) assumes a failed attempt
says something about the *backend*: the request was innocent, the replica
was not, so replaying the bytes elsewhere is free. A **poison request**
inverts that — a pathological body that wedges or crashes whatever engine
it lands on (a tokenizer edge case, a shape the warm ladder missed, a
grammar bomb). The retry machinery then becomes the attack's fan-out: the
gateway faithfully replays the same bytes into replica after replica, each
one stalling or entering recovery, until the whole fleet is down and the
breaker map is a wall of OPEN.

The quarantine breaks that loop with a strike ledger over request
**fingerprints**:

* the fingerprint is the FNV-1a hash of the request's full messages text
  (:func:`request_fingerprint`) — the same chained-hash machinery the
  router's prefix keys use (server/router.py), extended over the whole
  body so only byte-identical conversations share a fingerprint (two
  requests sharing a system prompt must never share a quarantine fate);
* every stall/crash/recovery event a fingerprint is implicated in is a
  **strike**: the gateway strikes on each proxy attempt that died with
  the request IN FLIGHT (zero-byte or midstream death after the bytes
  reached the replica — a connect-level refusal never strikes; the
  request never touched an engine) and on each forwarded 5xx that NAMES
  the fingerprint, and replicas strike when an engine failure kills the
  request server-side — reporting the fingerprint in the 5xx response
  (``X-DLT-Poison-Fp``) and in ``/health`` so direct clients and
  dashboards see the attribution. A plain 503 is never evidence: landing
  on an overloaded or rebuilding replica is not the request's fault;
* at ``limit`` strikes (``DLT_QUARANTINE_STRIKES``, default 2) the
  fingerprint is **quarantined**: the gateway stops retrying it and
  returns a terminal ``422`` (a client error — the request is the
  problem), and replicas refuse it outright before it can touch the
  engine. The waste it already caused is labeled ``quarantined`` in the
  goodput ledger (``dlt_wasted_tokens_total{reason="quarantined"}``).

The ledger is a bounded LRU (``DLT_QUARANTINE_SIZE``) with per-entry
expiry (``DLT_QUARANTINE_TTL_S``): a fingerprint that stops failing ages
out — a once-bad request must not be damned forever (the engine rebuild
that fixed the ladder hole also un-poisons the request).

Strike evidence is a heuristic — at the gateway, a crash-during-my-request
and a crash-because-of-my-request are indistinguishable from the wire
alone. The gateway therefore DISCOUNTS transport-death evidence from a
backend the fleet already knew was sick when the attempt died: breaker
not closed, fleet-table row gone stale, or the backend draining
(autoscaler or operator). Correlated replica deaths during a rolling
drain or a partial outage no longer terminally 422 an innocent
conversation (the PR 14 documented trade-off, resolved); a replica
NAMING the fingerprint (``X-DLT-Poison-Fp``) always strikes — that is
first-hand engine evidence, not a wire guess. The residual exposure —
two UNcorrelated hard kills of fresh, healthy replicas inside one TTL
window with the same innocent body in flight — is bounded by the TTL.
Stdlib-only: the gateway imports this on jax-free boxes.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from .router import _FNV64_OFFSET, fnv1a

#: response header a replica reports the implicated fingerprint on when an
#: engine failure kills a request (hex; rides the 5xx back to the gateway
#: and direct clients)
POISON_HEADER = "X-DLT-Poison-Fp"


def request_fingerprint(text: str | None) -> int | None:
    """The quarantine identity of one chat request: FNV-1a over the FULL
    messages text (server/router.py ``messages_prefix_text`` — the one
    hash-text builder both gateway and replica share). Unlike the router's
    block-chained prefix keys this covers every byte including the tail:
    requests are quarantined for what they ARE, not what they share."""
    if not text:
        return None
    return fnv1a(text.encode("utf-8", errors="replace"), _FNV64_OFFSET)


def fp_hex(fp: int) -> str:
    return f"{fp:016x}"


def parse_fp_hex(raw: str | None) -> int | None:
    try:
        return int(raw, 16) if raw else None
    except (TypeError, ValueError):
        return None


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


class QuarantineLedger:
    """Bounded, expiring strike counts per request fingerprint.

    One instance per gateway (strikes from the retry loop) and one per
    replica (strikes from engine-failure attribution); both run the same
    policy so a direct client and a routed client see the same verdict.
    Every method is one lock hold around a dict touch — per REQUEST, never
    per token."""

    def __init__(self, limit: int | None = None, size: int | None = None,
                 ttl_s: float | None = None):
        self.limit = limit if limit is not None else _env_int(
            "DLT_QUARANTINE_STRIKES", 2
        )
        self.size = size if size is not None else _env_int(
            "DLT_QUARANTINE_SIZE", 4096
        )
        self.ttl_s = ttl_s if ttl_s is not None else _env_float(
            "DLT_QUARANTINE_TTL_S", 600.0
        )
        self._lock = threading.Lock()
        # fp -> (strikes, last_strike_monotonic); LRU order = strike order
        self._strikes: "OrderedDict[int, tuple]" = OrderedDict()
        self.quarantined_total = 0   # fingerprints that crossed the limit
        self.strikes_total = 0

    def _fresh_locked(self, fp: int, now: float) -> int:
        ent = self._strikes.get(fp)
        if ent is None:
            return 0
        strikes, last = ent
        if now - last > self.ttl_s:
            del self._strikes[fp]
            return 0
        return strikes

    def strike(self, fp: int | None, n: int = 1) -> int:
        """Record ``n`` implication events; returns the fingerprint's
        fresh strike count (0 for None fingerprints — unparsable bodies
        have nothing to quarantine; the 400 path owns those)."""
        if fp is None:
            return 0
        now = time.monotonic()
        with self._lock:
            strikes = self._fresh_locked(fp, now) + n
            crossed = (
                self.limit > 0
                and strikes >= self.limit
                and strikes - n < self.limit
            )
            self._strikes[fp] = (strikes, now)
            self._strikes.move_to_end(fp)
            while len(self._strikes) > self.size:
                self._strikes.popitem(last=False)
            self.strikes_total += n
            if crossed:
                self.quarantined_total += 1
        return strikes

    def is_quarantined(self, fp: int | None) -> bool:
        if fp is None or self.limit <= 0:
            # limit <= 0 DISABLES quarantining (the documented semantics
            # of DLT_QUARANTINE_STRIKES=0) — without this guard a zero
            # limit would invert into quarantine-EVERYTHING (0 strikes >=
            # limit 0), a 100% outage from the off switch
            return False
        now = time.monotonic()
        with self._lock:
            return self._fresh_locked(fp, now) >= self.limit

    def strikes(self, fp: int | None) -> int:
        if fp is None:
            return 0
        with self._lock:
            return self._fresh_locked(fp, time.monotonic())

    # -- crash-only recovery (server/recovery.py) ---------------------------

    def dump(self) -> dict:
        """The ``GET /debug/quarantine`` payload: EVERY fresh entry (not
        just the snapshot's top-N) with its age, so a warm-restarting
        gateway can re-learn strike ledgers — and in-force 422s — from the
        fleet with TTL-correct remaining lifetimes."""
        now = time.monotonic()
        with self._lock:
            entries = [
                {"fp": fp_hex(fp), "strikes": s, "age_s": round(now - last, 3)}
                for fp, (s, last) in self._strikes.items()
                if now - last <= self.ttl_s
            ]
        return {"limit": self.limit, "ttl_s": self.ttl_s, "entries": entries}

    def prime(self, fp: int | None, strikes: int, age_s: float = 0.0) -> None:
        """Seed one recovered entry: the count becomes ``max(existing,
        strikes)`` (idempotent — recovery may merge several sources) and
        the strike clock is backdated by ``age_s`` so a recovered entry
        expires when the original would have, not TTL-from-restart."""
        if fp is None or strikes <= 0:
            return
        now = time.monotonic()
        at = now - max(age_s, 0.0)
        if now - at > self.ttl_s:
            return  # already expired at the source — nothing to recover
        with self._lock:
            existing = self._fresh_locked(fp, now)
            crossed = (
                self.limit > 0 and strikes >= self.limit
                and existing < self.limit
            )
            if strikes <= existing:
                return
            self._strikes[fp] = (strikes, at)
            self._strikes.move_to_end(fp)
            while len(self._strikes) > self.size:
                self._strikes.popitem(last=False)
            if crossed:
                self.quarantined_total += 1

    def snapshot(self, top_n: int = 16) -> dict:
        """The operator view (``/stats`` quarantine section; ``/health``
        carries the quarantined keys): hottest implicated fingerprints as
        hex, strike-count descending."""
        now = time.monotonic()
        with self._lock:
            live = [
                (fp, s) for fp, (s, last) in self._strikes.items()
                if now - last <= self.ttl_s
            ]
            live.sort(key=lambda kv: kv[1], reverse=True)
            return {
                "limit": self.limit,
                "ttl_s": self.ttl_s,
                "tracked": len(live),
                "strikes_total": self.strikes_total,
                "quarantined_total": self.quarantined_total,
                "implicated": [
                    {
                        "fp": fp_hex(fp), "strikes": s,
                        "quarantined": s >= self.limit,
                    }
                    for fp, s in live[:top_n]
                ],
            }
