"""Tokenizer, sampler, chat templates, and stop-sequence detection.

Re-implements the reference's capability surface (reference:
src/tokenizer.{hpp,cpp}) in Python:

* score-based BPE encode with first-match-in-vocab-order special-token
  matching (same lookup order as the reference's findSpecialTokenStartWith)
  and best-pair merging (reference: tokenizer.cpp:311-390);
* UTF-8-safe streaming decoder that holds back incomplete multi-byte
  sequences between tokens (reference: tokenizer.cpp:225-289);
* chat templates llama2 / llama3 / deepseek3 / chatml, auto-detected from the
  tokenizer's HF template string (reference: tokenizer.cpp:549-637);
* multi-token stop-sequence ("EOS") detector (reference: tokenizer.cpp:639-725);
* sampler: argmax / multinomial / top-p with the same xorshift* RNG so seeded
  runs are reproducible against the reference (reference: tokenizer.cpp:25-36,
  426-512).

Sampling happens host-side on a single logits vector per step (the reference
does the same); the heavy softmax/top-k for MoE routing lives on-device in the
model code instead.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .formats.tfile import TokenizerData, read_tfile


class Tokenizer:
    def __init__(self, data: TokenizerData | str):
        if isinstance(data, str):
            data = read_tfile(data)
        self.data = data
        self.vocab: list[bytes] = data.vocab
        self.scores = data.scores
        self.bos_id = data.bos_id
        self.add_bos = data.add_bos
        self.eos_token_ids = list(data.eos_token_ids)
        self.chat_template = data.chat_template
        self.vocab_size = data.vocab_size
        # bos_id splits regular from special vocab — same (admittedly fragile)
        # assumption the reference makes (tokenizer.cpp:141-143)
        self.regular_vocab_size = data.regular_vocab_size
        self._regular_index = {
            self.vocab[i]: i for i in range(self.regular_vocab_size - 1, -1, -1)
        }
        self._special = [
            (self.vocab[i], i) for i in range(self.regular_vocab_size, self.vocab_size)
        ]
        self._decode_buf = b""
        # native C++ merge engine (native/bpe_encoder.cpp); None -> the
        # Python merge loop below, which is the semantic reference
        from .formats.native import NativeBpe

        self._native_bpe = NativeBpe.create(
            self.vocab, self.scores, self.regular_vocab_size
        )

    # -- encode ------------------------------------------------------------

    def encode(
        self, text: str | bytes, is_start: bool = True, add_special_tokens: bool = True
    ) -> list[int]:
        if isinstance(text, str):
            text = text.encode("utf-8")
        tokens: list[int] = []
        if is_start and self.add_bos and self.bos_id >= 0:
            tokens.append(self.bos_id)

        # greedy pass: match special tokens at each position, otherwise
        # accumulate bytes until they hit a regular vocab entry
        i = 0
        pending = b""
        while i < len(text):
            if add_special_tokens and not pending:
                matched = -1
                for piece, tid in self._special:
                    if text.startswith(piece, i):
                        matched = tid
                        i += len(piece)
                        break
                if matched >= 0:
                    tokens.append(matched)
                    continue
            pending += text[i : i + 1]
            i += 1
            tid = self._regular_index.get(pending)
            if tid is not None:
                tokens.append(tid)
                pending = b""
        if pending:
            raise ValueError(f"cannot tokenize bytes {pending!r} (not in vocab)")

        # identical candidate rules in both paths (pair lookups hit only the
        # regular index, so bos/special ids pass through them unmerged unless
        # a regular piece genuinely equals the concatenation — same as the
        # Python loop)
        if self._native_bpe is not None:
            return self._native_bpe.merge(tokens)
        return self._merge_py(tokens)

    def _merge_py(self, tokens: list[int]) -> list[int]:
        # Merge the best-scoring adjacent pair until no pair merges. Same
        # leftmost-max policy as the reference, but with cached per-pair merge
        # candidates so each iteration only re-evaluates the two pairs touched
        # by the previous merge (the reference rescans + re-concats every pair
        # every iteration).
        def pair_candidate(a: int, b: int):
            tid = self._regular_index.get(self.vocab[a] + self.vocab[b])
            return (self.scores[tid], tid) if tid is not None else None

        cand = [pair_candidate(tokens[j], tokens[j + 1]) for j in range(len(tokens) - 1)]
        while True:
            best_score, best_idx = -1e10, -1
            for j, c in enumerate(cand):
                if c is not None and c[0] > best_score:
                    best_score, best_idx = c[0], j
            if best_idx == -1:
                break
            tokens[best_idx : best_idx + 2] = [cand[best_idx][1]]
            del cand[best_idx]
            if best_idx < len(cand):
                cand[best_idx] = pair_candidate(tokens[best_idx], tokens[best_idx + 1])
            if best_idx > 0:
                cand[best_idx - 1] = pair_candidate(tokens[best_idx - 1], tokens[best_idx])
        return tokens

    # -- streaming decode --------------------------------------------------

    def reset_decoder(self):
        self._decode_buf = b""

    def stream_decoder(self) -> "StreamDecoder":
        """An INDEPENDENT streaming-decode state over this tokenizer's vocab
        — batch serving gives each concurrent row its own UTF-8 carry
        buffer instead of sharing the tokenizer's."""
        return StreamDecoder(self)

    def decode(self, token: int) -> str | None:
        """Streaming decode: returns printable text or None if the token only
        extended an incomplete UTF-8 sequence (or was bos/eos). Uses the
        tokenizer's own carry buffer (single-sequence use); see
        `stream_decoder` for independent per-row state."""
        out, self._decode_buf = _decode_step(self, self._decode_buf, token)
        return out

    def is_eos(self, token: int) -> bool:
        return token in self.eos_token_ids

    def piece(self, token: int) -> bytes:
        return self.vocab[token]


def _decode_step(tok: "Tokenizer", buf: bytes, token: int):
    """One streaming-decode step: (text|None, new_buf)."""
    if token == tok.bos_id:
        return None, buf
    if token in tok.eos_token_ids:
        if buf:
            return buf.decode("utf-8", errors="replace"), b""
        return None, buf
    buf = buf + tok.vocab[token]
    # find the longest prefix that is complete UTF-8
    cut = len(buf)
    # walk back over at most 3 trailing continuation-or-lead bytes
    for back in range(1, min(4, len(buf)) + 1):
        b = buf[-back]
        if b < 0x80:
            break  # ascii: everything is complete
        if b >= 0xC0:  # lead byte: is the sequence complete?
            need = 2 if b < 0xE0 else 3 if b < 0xF0 else 4
            if back < need:
                cut = len(buf) - back  # incomplete, hold back
            break
    if cut == 0:
        return None, buf
    out, buf = buf[:cut], buf[cut:]
    return (out.decode("utf-8", errors="replace") or None), buf


class StreamDecoder:
    """Per-row streaming decoder sharing a Tokenizer's vocab."""

    def __init__(self, tok: Tokenizer):
        self._tok = tok
        self._buf = b""

    def decode(self, token: int) -> str | None:
        out, self._buf = _decode_step(self._tok, self._buf, token)
        return out


# ---------------------------------------------------------------------------
# Sampler
# ---------------------------------------------------------------------------

def _random_u32(state: np.uint64) -> tuple[int, np.uint64]:
    # xorshift* identical to the reference (tokenizer.cpp:25-31)
    s = int(state)
    s ^= (s >> 12) & 0xFFFFFFFFFFFFFFFF
    s = (s ^ (s << 25)) & 0xFFFFFFFFFFFFFFFF
    s ^= s >> 27
    r = ((s * 0x2545F4914F6CDD1D) & 0xFFFFFFFFFFFFFFFF) >> 32
    return r, np.uint64(s)


class Sampler:
    """Temperature + softmax + top-p / argmax sampling on a host logits vector
    (reference: tokenizer.cpp:449-512)."""

    def __init__(self, vocab_size: int, temperature: float, topp: float, seed: int):
        self.vocab_size = vocab_size
        self.temperature = temperature
        self.topp = topp
        self._state = np.uint64(seed if seed != 0 else 0x9E3779B97F4A7C15)

    def set_temp(self, temperature: float):
        self.temperature = temperature

    def set_seed(self, seed: int):
        self._state = np.uint64(seed if seed != 0 else 0x9E3779B97F4A7C15)

    def _coin(self) -> float:
        r, self._state = _random_u32(self._state)
        return (r >> 8) / 16777216.0

    def sample(self, logits: np.ndarray) -> int:
        logits = np.asarray(logits, dtype=np.float32).reshape(-1)[: self.vocab_size]
        if self.temperature == 0.0:
            return int(np.argmax(logits))
        x = logits / self.temperature
        x = x - x.max()
        probs = np.exp(x)
        probs /= probs.sum()
        coin = self._coin()
        if self.topp <= 0 or self.topp >= 1:
            cdf = np.cumsum(probs)
            return int(np.searchsorted(cdf, coin, side="right").clip(0, self.vocab_size - 1))
        return self._sample_topp(probs, coin)

    def _sample_topp(self, probs: np.ndarray, coin: float) -> int:
        n = probs.size
        cutoff = (1.0 - self.topp) / max(n - 1, 1)
        idx = np.nonzero(probs >= cutoff)[0]
        order = idx[np.argsort(-probs[idx], kind="stable")]
        p = probs[order]
        csum = np.cumsum(p)
        over = np.nonzero(csum > self.topp)[0]
        last = over[0] if over.size else p.size - 1
        r = coin * csum[last]
        pick = np.searchsorted(csum[: last + 1], r, side="right")
        return int(order[min(pick, last)])


# ---------------------------------------------------------------------------
# Chat templates
# ---------------------------------------------------------------------------

TEMPLATE_UNKNOWN = 0
TEMPLATE_LLAMA2 = 1
TEMPLATE_LLAMA3 = 2
TEMPLATE_DEEP_SEEK3 = 3
TEMPLATE_CHATML = 4

_TEMPLATE_NAMES = {
    "llama2": TEMPLATE_LLAMA2,
    "llama3": TEMPLATE_LLAMA3,
    "deepSeek3": TEMPLATE_DEEP_SEEK3,
    "chatml": TEMPLATE_CHATML,
}


@dataclass
class ChatItem:
    role: str
    message: str


@dataclass
class GeneratedChat:
    content: str
    public_prompt: str | None = None


class ChatTemplateGenerator:
    """Renders chat turns into the model's prompt format, auto-detecting the
    dialect from the HF template string when not forced
    (reference: tokenizer.cpp:549-637)."""

    def __init__(self, type_: int = TEMPLATE_UNKNOWN, chat_template: str | None = None, eos: str = ""):
        if type_ == TEMPLATE_UNKNOWN:
            if not chat_template:
                raise ValueError("the tokenizer does not include chat template")
            if "[INST]" in chat_template:
                type_ = TEMPLATE_LLAMA2
            elif "<|start_header_id|>" in chat_template:
                type_ = TEMPLATE_LLAMA3
            elif "<｜Assistant｜>" in chat_template:
                type_ = TEMPLATE_DEEP_SEEK3
            elif "<|im_start|>" in chat_template:
                type_ = TEMPLATE_CHATML
            else:
                raise ValueError("not supported chat template")
        self.type = type_
        self.eos = eos

    @staticmethod
    def parse_type(name: str) -> int:
        if name in _TEMPLATE_NAMES:
            return _TEMPLATE_NAMES[name]
        raise ValueError(f"unknown chat template {name!r}")

    def generate(self, items: list[ChatItem], append_generation_prompt: bool = True) -> GeneratedChat:
        buf = []
        public_prompt = None
        eos = self.eos
        if self.type == TEMPLATE_LLAMA2:
            i = 0
            if len(items) >= 2 and items[0].role == "system" and items[1].role == "user":
                buf.append(
                    "[INST] <<SYS>>\n" + items[0].message + "\n<</SYS>>\n\n" + items[1].message + " [/INST]" + eos
                )
                i = 2
            for it in items[i:]:
                if it.role == "assistant":
                    buf.append(it.message + eos)
                elif it.role == "user":
                    buf.append("[INST] " + it.message + " [/INST]" + eos)
        elif self.type == TEMPLATE_LLAMA3:
            for it in items:
                buf.append("<|start_header_id|>" + it.role + "<|end_header_id|>\n\n" + it.message + eos)
            if append_generation_prompt:
                buf.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        elif self.type == TEMPLATE_DEEP_SEEK3:
            i = 0
            if items and items[0].role == "system":
                buf.append(items[0].message)
                i = 1
            for it in items[i:]:
                if it.role == "user":
                    buf.append("<｜User｜>" + it.message)
                elif it.role == "assistant":
                    buf.append("<｜Assistant｜>" + it.message)
            if append_generation_prompt:
                buf.append("<｜Assistant｜><think>\n")
                public_prompt = "<think>\n"
        elif self.type == TEMPLATE_CHATML:
            # NOTE: deliberate divergence — the reference appends the
            # generation prompt inside the per-item loop (tokenizer.cpp:624-634),
            # emitting "<|im_start|>assistant\n" after every turn, which is a
            # malformed ChatML prompt. We emit it once, at the end.
            for it in items:
                if it.role in ("system", "user", "assistant"):
                    buf.append("<|im_start|>" + it.role + "\n" + it.message + "<|im_end|>\n")
            if append_generation_prompt:
                buf.append("<|im_start|>assistant\n")
        return GeneratedChat("".join(buf), public_prompt)


# ---------------------------------------------------------------------------
# EOS / stop-sequence detector
# ---------------------------------------------------------------------------

EOS_NOT = 0
EOS_MAYBE = 1
EOS_FOUND = 2


class EosDetector:
    """Detects multi-token stop sequences in streamed text, buffering output
    that might be the beginning of a stop string
    (reference: tokenizer.cpp:639-725).

    ``padding_left``/``padding_right`` allow the stop string to appear with up
    to that many stray characters before/after it in the buffered window.
    """

    def __init__(self, stop_token_ids: list[int], stop_pieces: list[str], padding_left: int = 0, padding_right: int = 0):
        self.stop_token_ids = list(stop_token_ids)
        self.pieces = [p for p in stop_pieces if p]
        self.padding_left = padding_left
        self.padding_right = padding_right
        self._buf = ""
        self._eos_pos = -1

    def is_eos_token(self, token_id: int) -> bool:
        return token_id in self.stop_token_ids

    def append(self, token_id: int, piece: str | None) -> int:
        if piece:
            self._buf += piece
        if self.is_eos_token(token_id):
            self._eos_pos = len(self._buf)
            return EOS_FOUND
        self._eos_pos = -1
        for p in self.pieces:
            if len(self._buf) > len(p) + self.padding_left + self.padding_right:
                continue
            for lo in range(self.padding_left + 1):
                n = len(self._buf) - lo
                if n <= 0 or n > len(p) + self.padding_right:
                    continue
                n = min(n, len(p))
                if self._buf[lo : lo + n] == p[:n]:
                    if n == len(p):
                        self._eos_pos = lo
                        self._buf = self._buf[:lo]
                        return EOS_FOUND
                    return EOS_MAYBE
        return EOS_NOT

    def get_delta(self) -> str | None:
        """Text that is now safe to emit (call after append returns NOT_EOS or
        FOUND); None if nothing to emit."""
        if not self._buf:
            return None
        if self._eos_pos == 0:
            return None
        return self._buf

    def reset(self):
        self._buf = ""
        self._eos_pos = -1
