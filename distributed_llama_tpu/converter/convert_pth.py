"""Original Meta-distribution Llama checkpoint (`consolidated.*.pth` +
`params.json`) -> `.m` model file.

Mirrors the reference converter's behavior exactly
(reference: converter/convert-llama.py):

* same tensor write order (embedding, then per layer wq wk wv wo w1 w2 w3
  attention_norm ffn_norm, then norm, output);
* multi-shard concatenation: axis 1 for `tok_embeddings`/`wo`/`w2`
  (column-split in the Meta sharding), axis 0 otherwise; 1-D tensors taken
  from the first shard (convert-llama.py:74-92);
* NO q/k permute — Meta layout is already interleaved-rope, matching the
  runtime's Llama rope (the HF converter's permute exists to undo HF's
  NeoX re-layout);
* header from params.json (n_kv_heads defaults to n_heads, rope_theta
  truncated to int, vocab_size must be patched positive —
  convert-llama.py:14-27); hidden_dim inferred from w1's first axis times
  the shard count (convert-llama.py:65).

Torch is NOT a dependency: the `.pth` zip container's `data.pkl` is parsed
with a restricted unpickler that understands exactly the torch tensor
rebuild protocol (persistent-id storages + `_rebuild_tensor_v2`), and the
raw storages are read straight from the zip — the same hand-rolled-format
stance as the sentencepiece protobuf reader (convert_tokenizer_spm.py).
"""

from __future__ import annotations

import json
import os
import pickle
import struct
import zipfile
from dataclasses import dataclass
from pathlib import Path

import numpy as np

from ..formats import mfile
from ..formats.mfile import ArchType, MFileWriter
from ..formats.quants import FloatType

# torch storage class name -> (numpy reader dtype, bytes per element)
_STORAGE_DTYPES = {
    "FloatStorage": ("<f4", 4),
    "HalfStorage": ("<f2", 2),
    "BFloat16Storage": ("<u2", 2),  # raw bits; converted below
    "DoubleStorage": ("<f8", 8),
}


@dataclass
class _Storage:
    key: str
    dtype_name: str
    numel: int


class _StorageRef:
    """Marker class the unpickler maps torch.*Storage names onto."""

    def __init__(self, name: str):
        self.name = name


def _bf16_bits_to_f32(bits: np.ndarray) -> np.ndarray:
    return (bits.astype(np.uint32) << 16).view(np.float32)


class _TorchUnpickler(pickle.Unpickler):
    """Restricted unpickler for torch checkpoint `data.pkl` files: resolves
    only the symbols the tensor protocol needs and REFUSES everything else
    (a .pth is arbitrary pickle; this never executes foreign constructors)."""

    def find_class(self, module, name):
        if module == "torch._utils" and name in (
            "_rebuild_tensor_v2", "_rebuild_tensor",
        ):
            def rebuild(storage, storage_offset, size, stride, *unused):
                return {"storage": storage, "offset": storage_offset,
                        "size": tuple(size), "stride": tuple(stride)}
            return rebuild
        if module == "torch" and name in _STORAGE_DTYPES:
            return _StorageRef(name)
        if module == "collections" and name == "OrderedDict":
            import collections

            return collections.OrderedDict
        raise pickle.UnpicklingError(f"refusing to load {module}.{name}")

    def persistent_load(self, pid):
        # ('storage', StorageRef, key, location, numel)
        if not (isinstance(pid, tuple) and pid and pid[0] == "storage"):
            raise pickle.UnpicklingError(f"unexpected persistent id {pid!r}")
        ref, key, _loc, numel = pid[1], pid[2], pid[3], pid[4]
        name = ref.name if isinstance(ref, _StorageRef) else str(ref)
        return _Storage(key=str(key), dtype_name=name, numel=int(numel))


class PthReader:
    """Lazy tensor access into one `.pth` zip checkpoint."""

    def __init__(self, path: str):
        self.zf = zipfile.ZipFile(path)
        names = self.zf.namelist()
        pkl = next((n for n in names if n.endswith("/data.pkl")), None)
        if pkl is None:
            raise ValueError(
                f"{path}: not a zip-format torch checkpoint (no data.pkl); "
                "legacy tar-format .pth files are not supported"
            )
        self.prefix = pkl[: -len("data.pkl")]
        with self.zf.open(pkl) as f:
            self.manifest = dict(_TorchUnpickler(f).load())

    def close(self):
        self.zf.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def keys(self):
        return self.manifest.keys()

    def get(self, name: str) -> np.ndarray:
        ent = self.manifest[name]
        st: _Storage = ent["storage"]
        dtype_str, esize = _STORAGE_DTYPES[st.dtype_name]
        raw = self.zf.read(f"{self.prefix}data/{st.key}")
        arr = np.frombuffer(raw, dtype=dtype_str, count=st.numel)
        if st.dtype_name == "BFloat16Storage":
            arr = _bf16_bits_to_f32(arr)
        # contiguous-only: Meta checkpoints store dense row-major tensors
        expect = []
        acc = 1
        for s in reversed(ent["size"]):
            expect.append(acc)
            acc *= s
        if ent["size"] and tuple(reversed(expect)) != ent["stride"]:
            raise ValueError(f"{name}: non-contiguous stride {ent['stride']}")
        n = int(np.prod(ent["size"])) if ent["size"] else 1
        arr = arr[ent["offset"] : ent["offset"] + n].reshape(ent["size"])
        return arr.astype(np.float32)


def header_kv_from_params(params: dict, weight_type: int, hidden_dim: int,
                          max_seq_len: int = 0) -> dict:
    if params.get("vocab_size", -1) < 1:
        raise ValueError(
            "vocab_size is invalid, please update params.json "
            "(reference converter requires the same patch)"
        )
    if params.get("max_seq_len") is None:
        # real Meta params.json files carry no max_seq_len — the reference
        # demands a manual params.json patch; here --max-seq-len can supply
        # it directly
        if not max_seq_len:
            raise ValueError(
                "max_seq_len is required: add it to params.json or pass "
                "--max-seq-len"
            )
        seq_len = int(max_seq_len)
    else:
        seq_len = int(params["max_seq_len"])
        if max_seq_len and seq_len > max_seq_len:
            seq_len = max_seq_len
    kv = {
        mfile.K_VERSION: 0,
        mfile.K_ARCH_TYPE: ArchType.LLAMA,
        mfile.K_DIM: int(params["dim"]),
        mfile.K_HIDDEN_DIM: hidden_dim,
        mfile.K_N_LAYERS: int(params["n_layers"]),
        mfile.K_N_HEADS: int(params["n_heads"]),
        mfile.K_N_KV_HEADS: int(params.get("n_kv_heads") or params["n_heads"]),
        mfile.K_N_EXPERTS: 0,
        mfile.K_N_ACTIVE_EXPERTS: 0,
        mfile.K_VOCAB_SIZE: int(params["vocab_size"]),
        mfile.K_SEQ_LEN: seq_len,
        mfile.K_HIDDEN_ACT: 1,  # silu (all Meta Llama lineages)
        mfile.K_WEIGHT_FLOAT_TYPE: weight_type,
    }
    if "rope_theta" in params:
        kv[mfile.K_ROPE_THETA] = int(params["rope_theta"])
    eps = params.get("norm_eps", 1e-5)
    import math

    eps_code = round(-math.log10(eps))
    if eps_code not in (5, 6) or abs(eps - 10.0**-eps_code) > 1e-12:
        raise ValueError(f"unsupported norm_eps {eps}")
    kv[mfile.K_NORM_EPSILON] = eps_code
    return kv


# shards concatenate on axis 1 for these (column-split in the Meta layout)
def _concat_axis(name: str) -> int:
    if (
        name == "tok_embeddings.weight"
        or name.endswith(".attention.wo.weight")
        or name.endswith(".feed_forward.w2.weight")
    ):
        return 1
    return 0


def convert_llama_pth(
    model_dir: str,
    out_path: str,
    weight_type_name: str = "q40",
    max_seq_len: int = 0,
    progress=print,
) -> None:
    """Convert a Meta-distribution Llama checkpoint directory to `.m`."""
    with open(os.path.join(model_dir, "params.json")) as f:
        params = json.load(f)
    shards = [
        PthReader(str(p))
        for p in sorted(Path(model_dir).glob("consolidated.*.pth"))
    ]
    if not shards:
        raise FileNotFoundError(f"no consolidated.*.pth in {model_dir}")
    wt = FloatType.parse(weight_type_name)
    n_layers = int(params["n_layers"])
    hidden_dim = shards[0].get("layers.0.feed_forward.w1.weight").shape[0] * len(shards)
    kv = header_kv_from_params(params, wt, hidden_dim, max_seq_len=max_seq_len)

    def merged(name: str) -> np.ndarray:
        parts = [s.get(name) for s in shards]
        if len(parts) == 1 or parts[0].ndim == 1:
            return parts[0]
        return np.concatenate(parts, axis=_concat_axis(name))

    with MFileWriter(out_path, kv) as out:
        def write(ft, name):
            w = merged(name)
            progress(f"🔶 writing {name} {tuple(w.shape)}")
            out.write_tensor(w, ft)

        write(FloatType.F32, "tok_embeddings.weight")
        for l in range(n_layers):
            pre = f"layers.{l}"
            write(wt, f"{pre}.attention.wq.weight")
            write(wt, f"{pre}.attention.wk.weight")
            write(wt, f"{pre}.attention.wv.weight")
            write(wt, f"{pre}.attention.wo.weight")
            write(wt, f"{pre}.feed_forward.w1.weight")
            write(wt, f"{pre}.feed_forward.w2.weight")
            write(wt, f"{pre}.feed_forward.w3.weight")
            write(FloatType.F32, f"{pre}.attention_norm.weight")
            write(FloatType.F32, f"{pre}.ffn_norm.weight")
        write(FloatType.F32, "norm.weight")
        write(wt, "output.weight")
    progress(f"✅ wrote {out_path}")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="convert-llama")
    p.add_argument("model_dir")
    p.add_argument("weight_type", choices=["f32", "f16", "q40", "q80"])
    p.add_argument("--max-seq-len", type=int, default=0)
    args = p.parse_args(argv)
    name = os.path.basename(os.path.normpath(args.model_dir)).lower()
    convert_llama_pth(
        args.model_dir,
        f"dllama_model_{name}_{args.weight_type}.m",
        args.weight_type,
        max_seq_len=args.max_seq_len,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
