"""Offline tooling: HF checkpoint -> `.m`, HF/sentencepiece tokenizer -> `.t`.

Python ports of the reference converter pipeline (reference: converter/
convert-hf.py, convert-tokenizer-hf.py, writer.py, tokenizer-writer.py)
built on this package's own format writers (formats/mfile.py, formats/
tfile.py), so converted files are readable by both this framework and the
reference engine.
"""

from .convert_hf import convert_hf, load_hf_config

__all__ = ["convert_hf", "load_hf_config"]
