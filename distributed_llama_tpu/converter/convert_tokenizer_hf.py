"""HF fast-tokenizer folder -> `.t` tokenizer file.

Port of the reference tokenizer converter (reference:
converter/convert-tokenizer-hf.py): vocab ids decode through the GPT-2
unicode->byte table, scores are ``-id`` (so BPE merge order follows id
order), bos/eos come from tokenizer_config.json / config.json, and the HF
chat template string ships inside the `.t` for runtime auto-detection.
"""

from __future__ import annotations

import json
import os

from ..formats.tfile import TokenizerData, write_tfile


def unicode_to_bytes() -> dict[str, int]:
    # GPT-2 byte-encoder table (reference: convert-tokenizer-hf.py:12-24)
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("¡"), ord("¬") + 1))
        + list(range(ord("®"), ord("ÿ") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(2**8):
        if b not in bs:
            bs.append(b)
            cs.append(2**8 + n)
            n += 1
    return dict(zip([chr(c) for c in cs], bs))


def _open_json(path: str):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def convert_tokenizer_hf(folder: str, out_path: str) -> TokenizerData:
    from transformers import PreTrainedTokenizerFast

    utb = unicode_to_bytes()
    tok = PreTrainedTokenizerFast(tokenizer_file=os.path.join(folder, "tokenizer.json"))
    vocab: list[bytes] = []
    scores: list[float] = []
    for i in range(len(tok.get_vocab())):
        chars = list(tok.convert_ids_to_tokens([i])[0])
        token_bytes = b""
        for ch in chars:
            if ch in utb:
                token_bytes += bytes([utb[ch]])
            else:
                token_bytes += ch.encode("utf-8")
        vocab.append(token_bytes)
        scores.append(-float(i))

    bos_id = tok.bos_token_id
    eos_ids = [tok.eos_token_id] if tok.eos_token_id is not None else None
    if bos_id is None or eos_ids is None:
        config = _open_json(os.path.join(folder, "config.json"))
        if bos_id is None:
            bos_id = config["bos_token_id"]
        if eos_ids is None:
            e = config["eos_token_id"]
            eos_ids = e if isinstance(e, list) else [e]

    chat_template = None
    tc_path = os.path.join(folder, "tokenizer_config.json")
    if os.path.exists(tc_path):
        tc = _open_json(tc_path)
        chat_template = tc.get("chat_template")
        add_bos = tc.get("add_bos_token", True)
    else:
        add_bos = True

    data = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id if bos_id is not None else -1,
        eos_token_ids=eos_ids,
        add_bos=bool(add_bos),
        chat_template=chat_template,
        max_token_length=max((len(v) for v in vocab), default=1),
    )
    write_tfile(out_path, data)
    return data


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="convert-tokenizer-hf")
    p.add_argument("folder")
    p.add_argument("name")
    args = p.parse_args(argv)
    convert_tokenizer_hf(args.folder, f"dllama_tokenizer_{args.name}.t")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
