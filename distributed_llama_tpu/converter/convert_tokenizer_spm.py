"""Sentencepiece / original-distribution tokenizers -> `.t` files.

Capability port of the reference's two non-HF tokenizer converters:

* ``convert_tokenizer_spm`` — reference converter/convert-tokenizer-llama2.py:
  enumerate a sentencepiece ``tokenizer.model``'s (piece, score) pairs,
  replace the sentencepiece whitespace marker ``\u2581`` with a space, carry
  bos/eos from the model's trainer spec, and embed the llama2 chat template.
  The reference drives the ``sentencepiece`` library for this; that package
  is not available here, so `parse_spm_model` walks the protobuf wire format
  of the .model file directly (the fields used are stable public contract:
  sentencepiece_model.proto — pieces field 1 {piece=1, score=2}, trainer_spec
  field 2 {unk_id=40, bos_id=41, eos_id=42}).
* ``convert_tokenizer_llama3`` — reference converter/convert-tokenizer-llama3.py:
  the original-distribution Llama-3 tiktoken-format file (base64 token +
  rank per line), scores = -rank, plus the fixed 256 special tokens and the
  llama3 chat template.
"""

from __future__ import annotations

import base64
import struct

from ..formats.tfile import TokenizerData, write_tfile

# chat template strings are format data shipped inside the .t — they must
# byte-match what the reference embeds (reference:
# converter/convert-tokenizer-llama2.py:6, convert-tokenizer-llama3.py:31)
LLAMA2_CHAT_TEMPLATE = (
    "{% if messages[0]['role'] == 'system' %}{% set loop_messages = messages[1:] %}"
    "{% set system_message = messages[0]['content'] %}{% else %}"
    "{% set loop_messages = messages %}{% set system_message = false %}{% endif %}"
    "{% for message in loop_messages %}"
    "{% if (message['role'] == 'user') != (loop.index0 % 2 == 0) %}"
    "{{ raise_exception('Conversation roles must alternate user/assistant/user/assistant/...') }}"
    "{% endif %}{% if loop.index0 == 0 and system_message != false %}"
    "{% set content = '<<SYS>>\\n' + system_message + '\\n<</SYS>>\\n\\n' + message['content'] %}"
    "{% else %}{% set content = message['content'] %}{% endif %}"
    "{% if message['role'] == 'user' %}{{ bos_token + '[INST] ' + content.strip() + ' [/INST]' }}"
    "{% elif message['role'] == 'assistant' %}{{ ' '  + content.strip() + ' ' + eos_token }}"
    "{% endif %}{% endfor %}"
)

LLAMA3_CHAT_TEMPLATE = (
    "{% set loop_messages = messages %}{% for message in loop_messages %}"
    "{% set content = '<|start_header_id|>' + message['role'] + '<|end_header_id|>\n\n'"
    "+ message['content'] | trim + '<|eot_id|>' %}"
    "{% if loop.index0 == 0 %}{% set content = bos_token + content %}{% endif %}"
    "{{ content }}{% endfor %}{% if add_generation_prompt %}"
    "{{ '<|start_header_id|>assistant<|end_header_id|>\n\n' }}{% endif %}"
)


# ---------------------------------------------------------------------------
# Minimal protobuf wire-format reader (no deps). Wire types: 0 varint,
# 1 fixed64, 2 length-delimited, 5 fixed32.
# ---------------------------------------------------------------------------


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    out = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, pos
        shift += 7


def _fields(data: bytes):
    """Yield (field_number, wire_type, value) over one message's bytes.
    Length-delimited values come out as bytes; varints as int; fixed32/64 as
    raw 4/8 bytes."""
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = _read_varint(data, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:
            v, pos = _read_varint(data, pos)
        elif wt == 1:
            v, pos = data[pos : pos + 8], pos + 8
        elif wt == 2:
            ln, pos = _read_varint(data, pos)
            v, pos = data[pos : pos + ln], pos + ln
        elif wt == 5:
            v, pos = data[pos : pos + 4], pos + 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wt}")
        yield field, wt, v


def parse_spm_model(path: str):
    """sentencepiece .model -> (pieces: list[(str piece, float score)],
    bos_id, eos_id). Equivalent of the reference's SentencePieceProcessor
    enumeration (id_to_piece/get_score/bos_id/eos_id)."""
    with open(path, "rb") as f:
        blob = f.read()
    pieces: list[tuple[str, float]] = []
    bos_id, eos_id = 1, 2  # sentencepiece trainer defaults
    for field, wt, v in _fields(blob):
        if field == 1 and wt == 2:  # repeated SentencePiece
            piece, score = "", 0.0
            for f2, wt2, v2 in _fields(v):
                if f2 == 1 and wt2 == 2:
                    piece = v2.decode("utf-8")
                elif f2 == 2 and wt2 == 5:
                    (score,) = struct.unpack("<f", v2)
            pieces.append((piece, score))
        elif field == 2 and wt == 2:  # TrainerSpec
            for f2, wt2, v2 in _fields(v):
                if f2 == 41 and wt2 == 0:
                    bos_id = v2
                elif f2 == 42 and wt2 == 0:
                    eos_id = v2
    if not pieces:
        raise ValueError(f"{path}: no sentencepiece pieces found")
    return pieces, bos_id, eos_id


def convert_tokenizer_spm(
    model_path: str,
    out_path: str,
    chat_template: str | None = LLAMA2_CHAT_TEMPLATE,
) -> TokenizerData:
    """Sentencepiece tokenizer.model -> .t (reference
    convert-tokenizer-llama2.py semantics: '\u2581' -> ' ', scores carried
    verbatim, bos/eos from the model)."""
    pieces, bos_id, eos_id = parse_spm_model(model_path)
    vocab = [p.replace("\u2581", " ").encode("utf-8") for p, _ in pieces]
    scores = [s for _, s in pieces]
    t = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=bos_id,
        eos_token_ids=[eos_id],
        add_bos=True,
        chat_template=chat_template,
        max_token_length=max(len(v) for v in vocab),
    )
    write_tfile(out_path, t)
    return t


N_LLAMA3_SPECIAL = 256
LLAMA3_SPECIAL_TOKENS = [
    "<|begin_of_text|>",
    "<|end_of_text|>",
    "<|reserved_special_token_0|>",
    "<|reserved_special_token_1|>",
    "<|reserved_special_token_2|>",
    "<|reserved_special_token_3|>",
    "<|start_header_id|>",
    "<|end_header_id|>",
    "<|reserved_special_token_4|>",
    "<|eot_id|>",
] + [f"<|reserved_special_token_{i}|>" for i in range(5, N_LLAMA3_SPECIAL - 5)]


def convert_tokenizer_llama3(model_path: str, out_path: str) -> TokenizerData:
    """Original-distribution Llama-3 tokenizer.model (tiktoken text format:
    'base64token rank' per line) -> .t (reference
    convert-tokenizer-llama3.py semantics)."""
    vocab: list[bytes] = []
    scores: list[float] = []
    with open(model_path, "r") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            b64, rank = line.split(" ")
            vocab.append(base64.b64decode(b64))
            scores.append(-float(rank))
    vocab += [s.encode("utf-8") for s in LLAMA3_SPECIAL_TOKENS]
    scores += [0.0] * N_LLAMA3_SPECIAL
    t = TokenizerData(
        vocab=vocab,
        scores=scores,
        bos_id=len(vocab) - N_LLAMA3_SPECIAL,  # 128000 for the real model
        eos_token_ids=[len(vocab) - N_LLAMA3_SPECIAL + 1, len(vocab) - N_LLAMA3_SPECIAL + 9],
        add_bos=True,
        chat_template=LLAMA3_CHAT_TEMPLATE,
        max_token_length=max(len(v) for v in vocab),
    )
    write_tfile(out_path, t)
    return t


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="convert-tokenizer-spm")
    p.add_argument("kind", choices=["spm", "llama2", "llama3"],
                   help="spm/llama2: sentencepiece .model; llama3: tiktoken text format")
    p.add_argument("model", help="path to tokenizer.model")
    p.add_argument("-o", "--output", default="tokenizer.t")
    args = p.parse_args(argv)
    if args.kind == "llama3":
        t = convert_tokenizer_llama3(args.model, args.output)
    else:
        t = convert_tokenizer_spm(args.model, args.output)
    print(f"✅ Created {args.output} ({t.vocab_size} tokens, bos={t.bos_id})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
