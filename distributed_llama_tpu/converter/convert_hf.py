"""HF safetensors checkpoint -> `.m` model file.

Mirrors the reference converter exactly (reference: converter/convert-hf.py):

* same tensor plan order as `formats.mfile.tensor_walk`;
* the Llama q/k **permute** (reference: convert-hf.py:13-16): HF stores q/k
  for half-split (NeoX) rope; the reference's runtime rope is interleaved
  (ropeLlama_F32), and the permute reorders head rows so the two are
  equivalent. Qwen3 keeps HF layout (Falcon/NeoX rope at runtime);
* `lm_head.weight` falls back to `model.embed_tokens.weight` for
  tied-embedding checkpoints (reference: convert-hf.py plan tail);
* header keys from config.json (arch/dims/rope/eps), f32 norm vectors, the
  chosen weight float type for matmul weights.

Implementation differences (host tooling, not TPU-relevant): tensors load
via `safetensors.numpy` per-tensor instead of torch, and files stream
one tensor at a time so peak memory is one tensor.
"""

from __future__ import annotations

import json
import os

import numpy as np

from ..formats import mfile
from ..formats.mfile import ArchType, MFileWriter
from ..formats.quants import FloatType


def permute_qk(w: np.ndarray, n_heads: int) -> np.ndarray:
    """Reorder rows of a [heads*head_dim, dim] projection from NeoX-rope
    layout to interleaved-rope layout (reference: convert-hf.py:13-16).

    Per head: rows [0..hd/2) and [hd/2..hd) interleave to (0, hd/2, 1,
    hd/2+1, ...), expressed as the reference's reshape/swapaxes dance.
    """
    rows = w.shape[0]
    head_dim = rows // n_heads
    return (
        w.reshape(n_heads, 2, head_dim // 2, *w.shape[1:])
        .swapaxes(1, 2)
        .reshape(w.shape)
    )


_ARCH = {
    "llama": ArchType.LLAMA,
    "mistral": ArchType.LLAMA,
    "qwen3": ArchType.QWEN3,
    "qwen3_moe": ArchType.QWEN3_MOE,
}
_ACT = {"gelu": 0, "silu": 1}


def load_hf_config(folder: str) -> dict:
    with open(os.path.join(folder, "config.json")) as f:
        return json.load(f)


def header_kv_from_config(config: dict, weight_type: int, max_seq_len: int = 0) -> dict:
    arch = _ARCH.get(config["model_type"])
    if arch is None:
        raise ValueError(f"unsupported arch type: {config['model_type']}")
    seq_len = config["max_position_embeddings"]
    if max_seq_len and seq_len > max_seq_len:
        seq_len = max_seq_len
    kv = {
        mfile.K_VERSION: 0,
        mfile.K_ARCH_TYPE: arch,
        mfile.K_DIM: config["hidden_size"],
        mfile.K_HIDDEN_DIM: config["intermediate_size"],
        mfile.K_N_LAYERS: config["num_hidden_layers"],
        mfile.K_N_HEADS: config["num_attention_heads"],
        mfile.K_N_KV_HEADS: config["num_key_value_heads"],
        mfile.K_N_EXPERTS: int(config.get("num_experts") or 0),
        mfile.K_N_ACTIVE_EXPERTS: int(config.get("num_experts_per_tok") or 0),
        mfile.K_VOCAB_SIZE: config["vocab_size"],
        mfile.K_SEQ_LEN: seq_len,
        mfile.K_HIDDEN_ACT: _ACT[config["hidden_act"]],
        mfile.K_WEIGHT_FLOAT_TYPE: weight_type,
    }
    if config.get("rope_theta") is not None:
        kv[mfile.K_ROPE_THETA] = int(config["rope_theta"])
    scaling = config.get("rope_scaling")
    if scaling is not None:
        if scaling.get("rope_type", scaling.get("type")) != "llama3":
            raise ValueError(f"unsupported rope scaling: {scaling}")
        kv[mfile.K_ROPE_SCALING_FACTOR] = int(scaling["factor"])
        kv[mfile.K_ROPE_SCALING_LOW_FREQ_FACTOR] = int(scaling["low_freq_factor"])
        kv[mfile.K_ROPE_SCALING_HIGH_FREQ_FACTORY] = int(scaling["high_freq_factor"])
        kv[mfile.K_ROPE_SCALING_ORIG_MAX_SEQ_LEN] = int(
            scaling["original_max_position_embeddings"]
        )
        kv[mfile.K_ROPE_TYPE] = mfile.RopeType.LLAMA3_1
    if config.get("head_dim"):
        kv[mfile.K_HEAD_DIM] = config["head_dim"]
    eps = config.get("rms_norm_eps", 1e-5)
    eps_code = round(-__import__("math").log10(eps))
    if eps_code not in (5, 6) or abs(eps - 10.0**-eps_code) > 1e-12:
        raise ValueError(
            f"unsupported rms_norm_eps {eps}: the .m format encodes only 1e-5/1e-6 "
            "(reference: src/llm.cpp:31-35)"
        )
    kv[mfile.K_NORM_EPSILON] = eps_code
    if config.get("moe_intermediate_size"):
        kv[mfile.K_MOE_HIDDEN_DIM] = config["moe_intermediate_size"]
    return kv


class _TensorSource:
    """Lazy multi-file safetensors lookup (numpy framework, one file open at
    a time — the reference converter's model-file walking, simplified)."""

    def __init__(self, folder: str):
        from safetensors import safe_open

        self._safe_open = safe_open
        self.files = sorted(
            os.path.join(folder, f)
            for f in os.listdir(folder)
            if f.endswith(".safetensors") and not f.startswith(".")
        )
        if not self.files:
            raise FileNotFoundError(f"no .safetensors files in {folder}")
        self.key_to_file: dict[str, str] = {}
        for path in self.files:
            with self._safe_open(path, framework="numpy") as f:
                for k in f.keys():
                    self.key_to_file[k] = path
        self._open_path = None
        self._open_file = None

    def get(self, *names: str) -> np.ndarray | None:
        for name in names:
            path = self.key_to_file.get(name)
            if path is None:
                continue
            if self._open_path != path:
                if self._open_file is not None:
                    del self._open_file
                self._open_file = self._safe_open(path, framework="numpy").__enter__()
                self._open_path = path
            return np.asarray(self._open_file.get_tensor(name), dtype=np.float32)
        return None


def convert_hf(
    folder: str,
    out_path: str,
    weight_type_name: str = "q40",
    max_seq_len: int = 0,
    progress=print,
) -> None:
    """Convert an HF checkpoint folder to a `.m` file."""
    config = load_hf_config(folder)
    wt = FloatType.parse(weight_type_name)
    kv = header_kv_from_config(config, wt, max_seq_len=max_seq_len)
    arch = kv[mfile.K_ARCH_TYPE]
    n_layers = kv[mfile.K_N_LAYERS]
    n_heads = kv[mfile.K_N_HEADS]
    n_kv_heads = kv[mfile.K_N_KV_HEADS]
    n_experts = kv[mfile.K_N_EXPERTS]
    is_qwen = arch in (ArchType.QWEN3, ArchType.QWEN3_MOE)
    src = _TensorSource(folder)

    def q_transform(w):
        # reference permute() collapses to kv-heads for k; for q it uses
        # n_heads (convert-hf.py:49-56)
        return permute_qk(w, n_heads) if arch == ArchType.LLAMA else w

    def k_transform(w):
        return permute_qk(w, n_kv_heads) if arch == ArchType.LLAMA else w

    with MFileWriter(out_path, kv) as out:
        def write(ft, *names, transform=None):
            w = src.get(*names)
            if w is None:
                raise KeyError(f"tensor not found: {names[0]}")
            if transform is not None:
                w = transform(w)
            progress(f"🔶 writing {names[0]} {tuple(w.shape)}")
            out.write_tensor(w, ft)

        write(FloatType.F32, "model.embed_tokens.weight")
        for l in range(n_layers):
            pre = f"model.layers.{l}"
            write(wt, f"{pre}.self_attn.q_proj.weight", transform=q_transform)
            write(wt, f"{pre}.self_attn.k_proj.weight", transform=k_transform)
            write(wt, f"{pre}.self_attn.v_proj.weight")
            write(wt, f"{pre}.self_attn.o_proj.weight")
            if n_experts > 0:
                write(FloatType.F32, f"{pre}.mlp.gate.weight")
                for e in range(n_experts):
                    write(wt, f"{pre}.mlp.experts.{e}.gate_proj.weight")
                    write(wt, f"{pre}.mlp.experts.{e}.down_proj.weight")
                    write(wt, f"{pre}.mlp.experts.{e}.up_proj.weight")
            else:
                write(wt, f"{pre}.mlp.gate_proj.weight")
                write(wt, f"{pre}.mlp.down_proj.weight")
                write(wt, f"{pre}.mlp.up_proj.weight")
            if is_qwen:
                write(FloatType.F32, f"{pre}.self_attn.q_norm.weight")
                write(FloatType.F32, f"{pre}.self_attn.k_norm.weight")
            write(FloatType.F32, f"{pre}.input_layernorm.weight")
            write(FloatType.F32, f"{pre}.post_attention_layernorm.weight")
        write(FloatType.F32, "model.norm.weight")
        write(wt, "lm_head.weight", "model.embed_tokens.weight")
    progress(f"✅ wrote {out_path}")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="convert-hf")
    p.add_argument("folder")
    p.add_argument("weight_type", choices=["f32", "f16", "q40", "q80"])
    p.add_argument("name")
    p.add_argument("--max-seq-len", type=int, default=0)
    args = p.parse_args(argv)
    convert_hf(
        args.folder,
        f"dllama_model_{args.name}_{args.weight_type}.m",
        args.weight_type,
        max_seq_len=args.max_seq_len,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
