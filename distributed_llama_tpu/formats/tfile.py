"""`.t` tokenizer-file codec.

Binary-compatible with the reference tokenizer format (reference:
src/tokenizer.cpp:42-166): magic ``0x567124``, int32 headerSize, (key, value)
int32 pairs, then optional chat-template bytes, optional EOS-token-id list,
then ``vocab_size`` records of ``(f32 score, int32 length, utf8 bytes)``.

The legacy magic ``0x567123`` (fixed struct header) is also accepted.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field

# header keys (reference: src/tokenizer.hpp:21-33)
TOK_VERSION = 0
TOK_VOCAB_SIZE = 1
MAX_TOKEN_LENGTH = 2
BOS_ID = 3
EOS_ID = 4  # legacy: single EOS id
PAD_ID = 5  # ignored
CHAT_EOS_ID = 6  # legacy
CHAT_TEMPLATE = 7
CHAT_STOP = 8  # ignored payload
N_EOS_TOKENS = 9
ADD_BOS = 10

OLD_MAGIC = 0x567123
MAGIC = 0x567124


@dataclass
class TokenizerData:
    vocab: list  # list[bytes]
    scores: list  # list[float]
    bos_id: int = -1
    eos_token_ids: list = field(default_factory=list)
    add_bos: bool = True
    chat_template: str | None = None
    max_token_length: int = 0

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    @property
    def regular_vocab_size(self) -> int:
        # The reference assumes bos_id splits regular and special vocab
        # (reference: src/tokenizer.cpp:141-143).
        return self.bos_id if self.bos_id >= 0 else self.vocab_size


def read_tfile(path: str) -> TokenizerData:
    with open(path, "rb") as f:
        data = f.read()
    (magic,) = struct.unpack_from("<i", data, 0)
    pos = 4
    t = TokenizerData(vocab=[], scores=[])
    n_eos = 0
    template_len = -1

    if magic == OLD_MAGIC:
        vocab_size, max_len, bos, eos, _pad = struct.unpack_from("<IIiii", data, pos)
        pos += 20
        t.max_token_length = max_len
        t.bos_id = bos
        t.eos_token_ids.append(eos)
        n_vocab = vocab_size
    elif magic == MAGIC:
        (header_size,) = struct.unpack_from("<i", data, pos)
        pos += 4
        n_kv = (header_size - 8) // 4
        vals = struct.unpack_from(f"<{n_kv}i", data, pos)
        pos += n_kv * 4
        version = -1
        n_vocab = 0
        skip = 0  # CHAT_STOP payload bytes to hop over, in key order
        for i in range(0, n_kv, 2):
            key, value = vals[i], vals[i + 1]
            if key == TOK_VERSION:
                version = value
            elif key == TOK_VOCAB_SIZE:
                n_vocab = value
            elif key == MAX_TOKEN_LENGTH:
                t.max_token_length = value
            elif key == BOS_ID:
                t.bos_id = value
            elif key in (EOS_ID, CHAT_EOS_ID):
                t.eos_token_ids.append(value)
            elif key == CHAT_TEMPLATE:
                template_len = value
            elif key == CHAT_STOP:
                skip += value
            elif key == PAD_ID:
                pass
            elif key == N_EOS_TOKENS:
                n_eos = value
            elif key == ADD_BOS:
                t.add_bos = value == 1
            else:
                raise ValueError(f"invalid tokenizer header key: {key}")
        if version != 1:
            raise ValueError("old tokenizer version, please regenerate your tokenizer")
        pos += skip
        if template_len > 0:
            t.chat_template = data[pos : pos + template_len].decode("utf-8")
            pos += template_len
        for _ in range(n_eos):
            (eid,) = struct.unpack_from("<i", data, pos)
            pos += 4
            t.eos_token_ids.append(eid)
    else:
        raise ValueError("invalid tokenizer file")

    if t.max_token_length < 1:
        raise ValueError("invalid tokenizer max token length")

    for _ in range(n_vocab):
        score, length = struct.unpack_from("<fi", data, pos)
        pos += 8
        t.scores.append(score)
        t.vocab.append(data[pos : pos + length])
        pos += length
    return t


def write_tfile(path: str, t: TokenizerData) -> None:
    kv: list[tuple[int, int]] = [
        (TOK_VERSION, 1),
        (TOK_VOCAB_SIZE, t.vocab_size),
        (MAX_TOKEN_LENGTH, max(1, t.max_token_length or max((len(v) for v in t.vocab), default=1))),
        (BOS_ID, t.bos_id),
        (ADD_BOS, 1 if t.add_bos else 0),
    ]
    template_bytes = t.chat_template.encode("utf-8") if t.chat_template else b""
    if template_bytes:
        kv.append((CHAT_TEMPLATE, len(template_bytes)))
    if t.eos_token_ids:
        kv.append((N_EOS_TOKENS, len(t.eos_token_ids)))

    with open(path, "wb") as f:
        body = b"".join(struct.pack("<ii", k, v) for k, v in kv)
        f.write(struct.pack("<ii", MAGIC, 8 + len(body)))
        f.write(body)
        f.write(template_bytes)
        for eid in t.eos_token_ids:
            f.write(struct.pack("<i", eid))
        for score, word in zip(t.scores, t.vocab):
            f.write(struct.pack("<fi", score, len(word)))
            f.write(word)
