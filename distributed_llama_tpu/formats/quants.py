"""Block-quantized tensor formats (Q40 / Q80), vectorized in numpy.

Binary layout is compatible with the reference engine's formats
(reference: src/nn/nn-quants.hpp:53-72, converter/writer.py:29-74):

* Q40: 32-element blocks -> 18 bytes: one float16 scale ``d`` followed by 16
  bytes of packed nibbles. Byte ``j`` holds element ``j`` in its low nibble and
  element ``j+16`` in its high nibble; dequant is ``(nibble - 8) * d``
  (reference: src/nn/nn-quants.cpp:229-246).
* Q80: 32-element blocks -> 34 bytes: float16 scale + 32 int8 values; dequant
  is ``q * d``.

On TPU we never compute on these layouts directly: Q40 weights are unpacked at
load time to an int8 tensor (values in [-8..7]) plus a per-block scale tensor,
which feed either an XLA dequant-matmul or the fused Pallas kernel
(ops/quant_matmul.py). This module is the host-side (numpy) codec.
"""

from __future__ import annotations

import numpy as np

Q_BLOCK = 32  # block size shared by Q40 and Q80
Q40_BLOCK_BYTES = 2 + Q_BLOCK // 2  # f16 scale + 16 nibble-pairs
Q80_BLOCK_BYTES = 2 + Q_BLOCK  # f16 scale + 32 int8


class FloatType:
    """Scalar type ids as encoded in .m headers (reference: nn-quants.hpp:57-62)."""

    UNK = -1
    F32 = 0
    F16 = 1
    Q40 = 2
    Q80 = 3

    _NAMES = {UNK: "unk", F32: "f32", F16: "f16", Q40: "q40", Q80: "q80"}

    @classmethod
    def name(cls, t: int) -> str:
        return cls._NAMES[t]

    @classmethod
    def parse(cls, s: str) -> int:
        for k, v in cls._NAMES.items():
            if v == s:
                return k
        raise ValueError(f"unknown float type: {s!r}")


def tensor_bytes(float_type: int, n_elements: int) -> int:
    """Serialized size of a flat tensor of ``n_elements`` in ``float_type``."""
    if float_type == FloatType.F32:
        return 4 * n_elements
    if float_type == FloatType.F16:
        return 2 * n_elements
    if float_type == FloatType.Q40:
        assert n_elements % Q_BLOCK == 0
        return (n_elements // Q_BLOCK) * Q40_BLOCK_BYTES
    if float_type == FloatType.Q80:
        assert n_elements % Q_BLOCK == 0
        return (n_elements // Q_BLOCK) * Q80_BLOCK_BYTES
    raise ValueError(f"unsupported float type {float_type}")


# ---------------------------------------------------------------------------
# Q40
# ---------------------------------------------------------------------------

def quantize_q40(x: np.ndarray) -> bytes:
    """Quantize a flat f32 array to Q40 bytes.

    Mirrors the converter's algorithm (reference: converter/writer.py:29-53):
    scale = extreme/-8 (the signed extreme, so the value furthest from zero maps
    to nibble 0 or 15), q = clip(x/d + 8.5, 0, 15) truncated.
    """
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    assert x.size % Q_BLOCK == 0, f"size {x.size} not a multiple of {Q_BLOCK}"
    groups = x.reshape(-1, Q_BLOCK)
    gmax = groups.max(axis=1)
    gmin = groups.min(axis=1)
    deltas = np.where(-gmin > gmax, gmin, gmax) / -8.0
    deltas16 = deltas.astype(np.float16)
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.clip(groups * inv[:, None] + 8.5, 0, 15).astype(np.int64)
    lo = q[:, : Q_BLOCK // 2] & 0xF
    hi = (q[:, Q_BLOCK // 2 :] & 0xF) << 4
    packed = (lo | hi).astype(np.uint8)

    out = np.empty((groups.shape[0], Q40_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = deltas16.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = packed
    return out.tobytes()


def unpack_q40(raw: bytes | np.ndarray, n_elements: int) -> tuple[np.ndarray, np.ndarray]:
    """Decode Q40 bytes into (int8 values in [-8,7], f16 per-block scales).

    Returns ``(q, d)`` with ``q.shape == (n_blocks, 32)`` int8 and
    ``d.shape == (n_blocks,)`` float16, such that dequant = q * d.
    This is the TPU load path: q and d are shipped to the device as-is.
    """
    assert n_elements % Q_BLOCK == 0
    n_blocks = n_elements // Q_BLOCK
    buf = np.frombuffer(raw, dtype=np.uint8, count=n_blocks * Q40_BLOCK_BYTES).reshape(
        n_blocks, Q40_BLOCK_BYTES
    )
    d = buf[:, :2].copy().view(np.float16).reshape(n_blocks)
    packed = buf[:, 2:]
    q = np.empty((n_blocks, Q_BLOCK), dtype=np.int8)
    q[:, : Q_BLOCK // 2] = (packed & 0x0F).astype(np.int8) - 8
    q[:, Q_BLOCK // 2 :] = (packed >> 4).astype(np.int8) - 8
    return q, d


def dequantize_q40(raw: bytes | np.ndarray, n_elements: int) -> np.ndarray:
    """Q40 bytes -> flat f32 array (reference: nn-quants.cpp:229-246)."""
    q, d = unpack_q40(raw, n_elements)
    return (q.astype(np.float32) * d.astype(np.float32)[:, None]).reshape(-1)


# ---------------------------------------------------------------------------
# Q80
# ---------------------------------------------------------------------------

def quantize_q80(x: np.ndarray) -> bytes:
    """Quantize a flat f32 array to Q80 bytes (reference: writer.py:55-74)."""
    x = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
    assert x.size % Q_BLOCK == 0
    groups = x.reshape(-1, Q_BLOCK)
    amax = np.abs(groups).max(axis=1)
    deltas = amax / 127.0
    deltas16 = deltas.astype(np.float16)
    inv = np.where(deltas != 0, np.divide(1.0, deltas, where=deltas != 0), 0.0)
    q = np.round(groups * inv[:, None]).astype(np.int8)

    out = np.empty((groups.shape[0], Q80_BLOCK_BYTES), dtype=np.uint8)
    out[:, :2] = deltas16.view(np.uint8).reshape(-1, 2)
    out[:, 2:] = q.view(np.uint8)
    return out.tobytes()


def dequantize_q80(raw: bytes | np.ndarray, n_elements: int) -> np.ndarray:
    """Q80 bytes -> flat f32 array."""
    assert n_elements % Q_BLOCK == 0
    n_blocks = n_elements // Q_BLOCK
    buf = np.frombuffer(raw, dtype=np.uint8, count=n_blocks * Q80_BLOCK_BYTES).reshape(
        n_blocks, Q80_BLOCK_BYTES
    )
    d = buf[:, :2].copy().view(np.float16).reshape(n_blocks).astype(np.float32)
    q = buf[:, 2:].view(np.int8).astype(np.float32)
    return (q * d[:, None]).reshape(-1)
