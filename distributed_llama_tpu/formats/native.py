"""ctypes loader for the native Q40 codec (native/q40_codec.cpp).

Builds the shared library on first use with g++ (cached next to the source;
rebuilt when the source is newer) and exposes `q40_unpack_t_native`. All
callers must tolerate `available() == False` (no compiler, sandboxed fs) and
fall back to the numpy codec in formats/quants.py — the native path is a
load-time accelerator, not a correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "q40_codec.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "libq40codec.so")


def _build() -> str | None:
    if not os.path.exists(_SRC):
        return None
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    # pid-suffixed temp: concurrent builders (server + CLI, pytest-xdist)
    # must not interleave writes into one temp file and install a corrupt .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread", _SRC, "-o", tmp]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return _SO
    except (OSError, subprocess.SubprocessError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return None


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if os.environ.get("DLT_NO_NATIVE"):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        lib.q40_unpack_t.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.q40_unpack_t.restype = None
        lib.q40_dequant.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.q40_dequant.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def q40_unpack_t_native(
    raw, out_f: int, in_f: int, n_threads: int = 0
) -> tuple[np.ndarray, np.ndarray] | None:
    """Q40 file bytes -> (qt [in_f//32, 32, out_f] int8, dt [in_f//32, out_f]
    f32) — the device T layout, in one pass. None if the codec is missing."""
    lib = _load()
    if lib is None:
        return None
    bpr = in_f // 32
    buf = np.frombuffer(raw, dtype=np.uint8, count=out_f * bpr * 18)
    qt = np.empty((bpr, 32, out_f), dtype=np.int8)
    dt = np.empty((bpr, out_f), dtype=np.float32)
    lib.q40_unpack_t(
        buf.ctypes.data, out_f, bpr,
        qt.ctypes.data, dt.ctypes.data, n_threads,
    )
    return qt, dt


def q40_dequant_native(raw, n_elements: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    n_blocks = n_elements // 32
    buf = np.frombuffer(raw, dtype=np.uint8, count=n_blocks * 18)
    out = np.empty(n_elements, dtype=np.float32)
    lib.q40_dequant(buf.ctypes.data, n_blocks, out.ctypes.data)
    return out
