"""ctypes loader for the native Q40 codec (native/q40_codec.cpp).

Builds the shared library on first use with g++ (cached next to the source;
rebuilt when the source is newer) and exposes `q40_unpack_t_native`. All
callers must tolerate `available() == False` (no compiler, sandboxed fs) and
fall back to the numpy codec in formats/quants.py — the native path is a
load-time accelerator, not a correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "q40_codec.cpp",
)
_SO = os.path.join(os.path.dirname(_SRC), "libq40codec.so")


def _build_and_load(src: str, so: str, extra_flags: tuple = ()):
    """Compile `src` to `so` if stale and dlopen it; None on any failure.
    Shared by every native library in this package — the build/caching and
    concurrency subtleties live in exactly one place."""
    if os.environ.get("DLT_NO_NATIVE"):
        return None
    if not os.path.exists(src):
        return None
    if not (os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src)):
        # pid-suffixed temp: concurrent builders (server + CLI, pytest-xdist)
        # must not interleave writes into one temp and install a corrupt .so
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", *extra_flags, src, "-o", tmp]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so)
        except (OSError, subprocess.SubprocessError):
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
    try:
        return ctypes.CDLL(so)
    except OSError:
        return None


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        lib = _build_and_load(_SRC, _SO, extra_flags=("-pthread",))
        if lib is None:
            return None
        lib.q40_unpack_t.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
        ]
        lib.q40_unpack_t.restype = None
        lib.q40_dequant.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
        lib.q40_dequant.restype = None
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def q40_unpack_t_native(
    raw, out_f: int, in_f: int, n_threads: int = 0
) -> tuple[np.ndarray, np.ndarray] | None:
    """Q40 file bytes -> (qt [in_f//32, 32, out_f] int8, dt [in_f//32, out_f]
    f16) — the device T layout, in one pass. The scale plane carries the
    file's f16 bits verbatim (bit-exact, half the f32 plane's traffic). None
    if the codec is missing."""
    lib = _load()
    if lib is None:
        return None
    bpr = in_f // 32
    buf = np.frombuffer(raw, dtype=np.uint8, count=out_f * bpr * 18)
    qt = np.empty((bpr, 32, out_f), dtype=np.int8)
    dt = np.empty((bpr, out_f), dtype=np.float16)
    lib.q40_unpack_t(
        buf.ctypes.data, out_f, bpr,
        qt.ctypes.data, dt.ctypes.data, n_threads,
    )
    return qt, dt


def q40_dequant_native(raw, n_elements: int) -> np.ndarray | None:
    lib = _load()
    if lib is None:
        return None
    n_blocks = n_elements // 32
    buf = np.frombuffer(raw, dtype=np.uint8, count=n_blocks * 18)
    out = np.empty(n_elements, dtype=np.float32)
    lib.q40_dequant(buf.ctypes.data, n_blocks, out.ctypes.data)
    return out


# ---------------------------------------------------------------------------
# Native BPE merge engine (native/bpe_encoder.cpp) — same loader contract:
# build-on-first-use, every caller tolerates unavailability and falls back to
# the Python merge loop in tokenizer.py (the semantic reference).
# ---------------------------------------------------------------------------

_BPE_SRC = os.path.join(os.path.dirname(_SRC), "bpe_encoder.cpp")
_BPE_SO = os.path.join(os.path.dirname(_SRC), "libbpeencoder.so")
_bpe_lib = None
_bpe_tried = False


def _load_bpe():
    global _bpe_lib, _bpe_tried
    with _lock:
        if _bpe_tried:
            return _bpe_lib
        _bpe_tried = True
        lib = _build_and_load(_BPE_SRC, _BPE_SO)
        if lib is None:
            return None
        lib.bpe_create.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_int32,
        ]
        lib.bpe_create.restype = ctypes.c_void_p
        lib.bpe_free.argtypes = [ctypes.c_void_p]
        lib.bpe_free.restype = None
        lib.bpe_merge.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        lib.bpe_merge.restype = ctypes.c_int64
        _bpe_lib = lib
        return _bpe_lib


class NativeBpe:
    """Handle over the C++ merge engine for one vocabulary. `create` returns
    None when the native path is unavailable."""

    @staticmethod
    def create(vocab: list, scores, n_regular: int) -> "NativeBpe | None":
        lib = _load_bpe()
        if lib is None:
            return None
        blob = b"".join(vocab)
        offsets = np.zeros(len(vocab) + 1, dtype=np.int64)
        np.cumsum([len(v) for v in vocab], out=offsets[1:])
        scores_arr = np.ascontiguousarray(scores, dtype=np.float32)
        buf = np.frombuffer(blob, dtype=np.uint8) if blob else np.zeros(1, np.uint8)
        handle = lib.bpe_create(
            buf.ctypes.data, offsets.ctypes.data, scores_arr.ctypes.data,
            len(vocab), n_regular,
        )
        if not handle:
            return None
        obj = NativeBpe()
        obj._lib = lib
        obj._handle = handle
        return obj

    def merge(self, tokens: list) -> list:
        arr = np.asarray(tokens, dtype=np.int32)
        new_n = self._lib.bpe_merge(self._handle, arr.ctypes.data, len(arr))
        return arr[:new_n].tolist()

    def __del__(self):
        lib = getattr(self, "_lib", None)
        handle = getattr(self, "_handle", None)
        if lib is not None and handle:
            lib.bpe_free(handle)
