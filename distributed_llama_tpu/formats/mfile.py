"""`.m` model-file codec: header parsing and the per-tensor walk.

Binary-compatible with the reference engine's model format:

* magic ``0xA00ABCD``, then ``headerSize`` (int32), then (key, value) int32
  pairs (reference: src/llm.cpp:37-121, converter/writer.py:108-150).
* tensor payload: a fixed walk order that both the converter and the weight
  loader agree on (reference: src/llm.cpp:658-713) —
  ``embedding; per layer: q,k,v,wo, [moe_gate, experts x (w1,w2,w3) | w1,w2,w3],
  [qwen3: q_norm,k_norm], norm0, norm1; final_norm; wcls``.

Float header values are stored as int32s and cast on read (so e.g. a rope
theta of 500000 is the int 500000); norm epsilon is encoded as the exponent
(5 -> 1e-5, 6 -> 1e-6; reference: src/llm.cpp:31-35).
"""

from __future__ import annotations

import mmap
import os
import struct
from dataclasses import dataclass

import numpy as np

from .quants import FloatType, tensor_bytes, dequantize_q40, dequantize_q80, unpack_q40

MAGIC = 0x0A00ABCD

# header keys (reference: src/llm.hpp:9-32)
K_VERSION = 0
K_ARCH_TYPE = 1
K_DIM = 2
K_HIDDEN_DIM = 3
K_N_LAYERS = 4
K_N_HEADS = 5
K_N_KV_HEADS = 6
K_N_EXPERTS = 7
K_N_ACTIVE_EXPERTS = 8
K_VOCAB_SIZE = 9
K_SEQ_LEN = 10
K_HIDDEN_ACT = 11
K_ROPE_THETA = 12
K_WEIGHT_FLOAT_TYPE = 13
K_ROPE_SCALING_FACTOR = 14
K_ROPE_SCALING_LOW_FREQ_FACTOR = 15
K_ROPE_SCALING_HIGH_FREQ_FACTORY = 16
K_ROPE_SCALING_ORIG_MAX_SEQ_LEN = 17
K_ROPE_TYPE = 18
K_HEAD_DIM = 19
K_NORM_EPSILON = 20
K_MOE_HIDDEN_DIM = 21


class ArchType:
    LLAMA = 0xABCD00
    QWEN3 = 0xABCD01
    QWEN3_MOE = 0xABCD02

    _NAMES = {LLAMA: "llama", QWEN3: "qwen3", QWEN3_MOE: "qwen3_moe"}

    @classmethod
    def name(cls, t: int) -> str:
        return cls._NAMES[t]


class HiddenAct:
    GELU = 0
    SILU = 1


class RopeType:
    LLAMA = 0
    FALCON = 1
    LLAMA3_1 = 2


@dataclass
class ModelHeader:
    """Parsed .m header (reference: src/llm.hpp:45-77)."""

    version: int = 0
    arch_type: int = ArchType.LLAMA
    dim: int = 0
    hidden_dim: int = 0
    moe_hidden_dim: int = 0
    n_layers: int = 0
    n_heads: int = 0
    n_kv_heads: int = 0
    n_experts: int = 0
    n_active_experts: int = 0
    vocab_size: int = 0
    seq_len: int = 0
    orig_seq_len: int = 0
    hidden_act: int = HiddenAct.SILU
    rope_theta: float = 10000.0
    rope_type: int = RopeType.LLAMA
    rope_scaling_factor: float = 1.0
    rope_scaling_low_freq_factor: float = 0.0
    rope_scaling_high_freq_factor: float = 0.0
    rope_scaling_orig_max_seq_len: int = 0
    norm_epsilon: float = 1e-5
    weight_type: int = FloatType.UNK
    head_dim: int = 0
    header_bytes: int = 0  # magic + size field + kv pairs
    file_bytes: int = 0

    @property
    def q_dim(self) -> int:
        return self.head_dim * self.n_heads

    @property
    def kv_dim(self) -> int:
        return self.head_dim * self.n_kv_heads

    @property
    def ff_dim(self) -> int:
        """Per-expert FFN width for MoE, dense FFN width otherwise."""
        return self.moe_hidden_dim if self.arch_type == ArchType.QWEN3_MOE else self.hidden_dim

    def finalize(self, max_seq_len: int = 0) -> "ModelHeader":
        """Apply derived-field defaults (reference: src/llm.cpp:105-117)."""
        self.orig_seq_len = self.seq_len
        if max_seq_len > 0 and self.seq_len > max_seq_len:
            self.seq_len = max_seq_len
        if self.head_dim == 0:
            self.head_dim = self.dim // self.n_heads
        if self.arch_type in (ArchType.QWEN3, ArchType.QWEN3_MOE):
            self.rope_type = RopeType.FALCON
        return self


@dataclass(frozen=True)
class TensorSpec:
    """One entry of the fixed tensor walk."""

    role: str  # embedding|q|k|v|wo|moe_gate|w1|w2|w3|q_norm|k_norm|norm0|norm1|final_norm|wcls
    layer: int  # -1 for global tensors
    expert: int  # -1 for non-expert tensors
    shape: tuple  # logical (out_features, in_features) or (n,) — torch row-major
    float_type: int
    offset: int  # byte offset of this tensor's payload within the file

    @property
    def name(self) -> str:
        parts = [self.role]
        if self.layer >= 0:
            parts.append(f"l{self.layer}")
        if self.expert >= 0:
            parts.append(f"e{self.expert}")
        return ".".join(parts)

    @property
    def n_elements(self) -> int:
        n = 1
        for s in self.shape:
            n *= s
        return n

    @property
    def n_bytes(self) -> int:
        return tensor_bytes(self.float_type, self.n_elements)


def tensor_walk(h: ModelHeader) -> list[TensorSpec]:
    """The fixed tensor order of a .m file (reference: src/llm.cpp:658-713).

    Shapes are torch-convention ``(out_features, in_features)`` with row-major
    flattening — i.e. ``q`` is ``(q_dim, dim)`` and a row-split over nodes
    slices its leading axis, matching ``splitRowMatmulWeight``
    (reference: src/nn/nn-core.cpp:291-324).
    """
    wt = h.weight_type
    specs: list[TensorSpec] = []
    off = h.header_bytes
    is_qwen = h.arch_type in (ArchType.QWEN3, ArchType.QWEN3_MOE)

    def add(role, layer, expert, shape, ft):
        nonlocal off
        s = TensorSpec(role, layer, expert, tuple(shape), ft, off)
        specs.append(s)
        off += s.n_bytes

    add("embedding", -1, -1, (h.vocab_size, h.dim), FloatType.F32)
    for l in range(h.n_layers):
        add("q", l, -1, (h.q_dim, h.dim), wt)
        add("k", l, -1, (h.kv_dim, h.dim), wt)
        add("v", l, -1, (h.kv_dim, h.dim), wt)
        add("wo", l, -1, (h.dim, h.q_dim), wt)
        if h.n_experts > 0:
            add("moe_gate", l, -1, (h.n_experts, h.dim), FloatType.F32)
            for e in range(h.n_experts):
                add("w1", l, e, (h.ff_dim, h.dim), wt)
                add("w2", l, e, (h.dim, h.ff_dim), wt)
                add("w3", l, e, (h.ff_dim, h.dim), wt)
        else:
            add("w1", l, -1, (h.ff_dim, h.dim), wt)
            add("w2", l, -1, (h.dim, h.ff_dim), wt)
            add("w3", l, -1, (h.ff_dim, h.dim), wt)
        if is_qwen:
            add("q_norm", l, -1, (h.head_dim,), FloatType.F32)
            add("k_norm", l, -1, (h.head_dim,), FloatType.F32)
        add("norm0", l, -1, (h.dim,), FloatType.F32)
        add("norm1", l, -1, (h.dim,), FloatType.F32)
    add("final_norm", -1, -1, (h.dim,), FloatType.F32)
    add("wcls", -1, -1, (h.vocab_size, h.dim), wt)
    return specs


class MFileReader:
    """mmap-backed .m reader: header + zero-copy per-tensor views.

    The reference's root node mmaps the file and streams split slices to
    workers over TCP (reference: src/llm.cpp:658-713); on TPU the analogue is
    mmap + per-tensor numpy views handed to `jax.device_put` with a
    `NamedSharding`, letting JAX ship each shard to its chip.
    """

    def __init__(self, path: str, max_seq_len: int = 0):
        self.path = path
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        self.header = _parse_header(self._mm, os.path.getsize(path)).finalize(max_seq_len)
        self.specs = tensor_walk(self.header)
        self.by_name = {s.name: s for s in self.specs}
        end = self.specs[-1].offset + self.specs[-1].n_bytes
        if end != self.header.file_bytes:
            raise ValueError(
                f"model file size mismatch: walk ends at {end}, file is {self.header.file_bytes} bytes"
            )

    def close(self):
        self._mm.close()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def raw(self, spec: TensorSpec) -> memoryview:
        return memoryview(self._mm)[spec.offset : spec.offset + spec.n_bytes]

    def tensor_f32(self, spec: TensorSpec) -> np.ndarray:
        """Dequantize/convert a tensor to f32 in its logical shape."""
        raw = self.raw(spec)
        n = spec.n_elements
        if spec.float_type == FloatType.F32:
            # copy so the returned array outlives the mmap (close() requires
            # no exported views)
            x = np.frombuffer(raw, dtype=np.float32, count=n).copy()
        elif spec.float_type == FloatType.F16:
            x = np.frombuffer(raw, dtype=np.float16, count=n).astype(np.float32)
        elif spec.float_type == FloatType.Q40:
            x = dequantize_q40(raw, n)
        elif spec.float_type == FloatType.Q80:
            x = dequantize_q80(raw, n)
        else:
            raise ValueError(f"unsupported float type {spec.float_type}")
        return x.reshape(spec.shape)

    def tensor_q40(self, spec: TensorSpec) -> tuple[np.ndarray, np.ndarray]:
        """Q40 tensor as (int8 q [out, in//32, 32], f16 scales [out, in//32])."""
        assert spec.float_type == FloatType.Q40 and len(spec.shape) == 2
        out_f, in_f = spec.shape
        q, d = unpack_q40(self.raw(spec), spec.n_elements)
        return q.reshape(out_f, in_f // 32, 32), d.reshape(out_f, in_f // 32)


def _parse_header(buf, file_size: int) -> ModelHeader:
    magic = struct.unpack_from("<i", buf, 0)[0]
    if magic in (0xABCD00, 0xABCD01):
        raise ValueError("old model format is not supported")
    if magic != MAGIC:
        raise ValueError(f"unsupported magic number 0x{magic:X}")
    header_size = struct.unpack_from("<i", buf, 4)[0]
    n_kv = (header_size - 8) // 4
    vals = struct.unpack_from(f"<{n_kv}i", buf, 8)

    h = ModelHeader()
    setters = {
        K_VERSION: lambda v: setattr(h, "version", v),
        K_ARCH_TYPE: lambda v: setattr(h, "arch_type", v),
        K_DIM: lambda v: setattr(h, "dim", v),
        K_HIDDEN_DIM: lambda v: setattr(h, "hidden_dim", v),
        K_N_LAYERS: lambda v: setattr(h, "n_layers", v),
        K_N_HEADS: lambda v: setattr(h, "n_heads", v),
        K_N_KV_HEADS: lambda v: setattr(h, "n_kv_heads", v),
        K_N_EXPERTS: lambda v: setattr(h, "n_experts", v),
        K_N_ACTIVE_EXPERTS: lambda v: setattr(h, "n_active_experts", v),
        K_VOCAB_SIZE: lambda v: setattr(h, "vocab_size", v),
        K_SEQ_LEN: lambda v: setattr(h, "seq_len", v),
        K_HIDDEN_ACT: lambda v: setattr(h, "hidden_act", v),
        K_ROPE_THETA: lambda v: setattr(h, "rope_theta", float(v)),
        K_WEIGHT_FLOAT_TYPE: lambda v: setattr(h, "weight_type", v),
        K_ROPE_SCALING_FACTOR: lambda v: setattr(h, "rope_scaling_factor", float(v)),
        K_ROPE_SCALING_LOW_FREQ_FACTOR: lambda v: setattr(h, "rope_scaling_low_freq_factor", float(v)),
        K_ROPE_SCALING_HIGH_FREQ_FACTORY: lambda v: setattr(h, "rope_scaling_high_freq_factor", float(v)),
        K_ROPE_SCALING_ORIG_MAX_SEQ_LEN: lambda v: setattr(h, "rope_scaling_orig_max_seq_len", v),
        K_ROPE_TYPE: lambda v: setattr(h, "rope_type", v),
        K_HEAD_DIM: lambda v: setattr(h, "head_dim", v),
        K_NORM_EPSILON: lambda v: setattr(h, "norm_epsilon", _norm_epsilon(v)),
        K_MOE_HIDDEN_DIM: lambda v: setattr(h, "moe_hidden_dim", v),
    }
    for i in range(0, n_kv, 2):
        key, value = vals[i], vals[i + 1]
        if key not in setters:
            raise ValueError(f"unsupported header key {key}")
        setters[key](value)
    if h.weight_type == FloatType.UNK:
        raise ValueError("model does not specify weight type")
    h.header_bytes = 8 + n_kv * 4
    h.file_bytes = file_size
    return h


def _norm_epsilon(v: int) -> float:
    # stored as the exponent (reference: src/llm.cpp:31-35)
    if v == 5:
        return 1e-5
    if v == 6:
        return 1e-6
    raise ValueError(f"unsupported norm epsilon code {v}")


class MFileWriter:
    """Writes .m files in the reference layout; used by the converter and by
    the synthetic-model generator in tests."""

    def __init__(self, path: str, header_kv: dict[int, int]):
        self._f = open(path, "wb")
        data = b"".join(struct.pack("<ii", k, v) for k, v in header_kv.items())
        self._f.write(struct.pack("<ii", MAGIC, 8 + len(data)))
        self._f.write(data)

    def write_tensor(self, x: np.ndarray, float_type: int):
        from .quants import quantize_q40, quantize_q80

        flat = np.ascontiguousarray(x, dtype=np.float32).reshape(-1)
        if float_type == FloatType.F32:
            self._f.write(flat.tobytes())
        elif float_type == FloatType.F16:
            self._f.write(flat.astype(np.float16).tobytes())
        elif float_type == FloatType.Q40:
            self._f.write(quantize_q40(flat))
        elif float_type == FloatType.Q80:
            self._f.write(quantize_q80(flat))
        else:
            raise ValueError(f"unsupported float type {float_type}")

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
