from .quants import (
    FloatType,
    Q_BLOCK,
    quantize_q40,
    dequantize_q40,
    quantize_q80,
    dequantize_q80,
    unpack_q40,
    tensor_bytes,
)
from .mfile import ArchType, HiddenAct, RopeType, ModelHeader, MFileReader, MFileWriter
from .tfile import TokenizerData, read_tfile, write_tfile
