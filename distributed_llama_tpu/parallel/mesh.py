"""Device mesh construction.

The mesh replaces the reference's socket full-mesh bootstrap
(reference: NnNetwork::serve/connect, src/nn/nn-network.cpp:516-629): there
is no handshake — the mesh is a logical view over `jax.devices()`, and the
axes carry the roles the reference encoded in its PPxTP rank layout:

  dp — data/replica parallel (reference: gateway-level request DP)
  pp — pipeline stages       (reference: ppRank, layer ranges)
  ep — expert parallel       (reference: TP-within-expert only; true expert
                              placement has no reference analogue)
  tp — tensor parallel       (reference: tpRank, head/ff split + all-reduce)
  sp — sequence parallel     (no reference analogue; long-context sharding)
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh

AXES = ("dp", "pp", "ep", "tp", "sp")


def make_mesh(
    tp: int = 1, pp: int = 1, dp: int = 1, sp: int = 1, ep: int = 1, devices=None
) -> Mesh:
    """Build a ("dp","pp","ep","tp","sp") mesh over the first
    dp*pp*ep*tp*sp devices.

    Axis order puts ep/tp/sp innermost so the per-layer collectives (TP
    all-reduce, EP combine-psum, SP softmax-combine) ride the fastest/nearest
    ICI links under the default device enumeration.
    """
    n = dp * pp * ep * tp * sp
    if devices is None:
        devices = jax.devices()
    if n > len(devices):
        raise ValueError(f"mesh needs {n} devices, have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(dp, pp, ep, tp, sp)  # dlt: allow(host-sync) — array of device handles, no data transfer
    return Mesh(arr, AXES)
