"""Parallel layer: topology, device mesh, shardings, and the explicit
shard_map pipeline.

This layer replaces the reference's entire distributed stack — the TCP socket
mesh, hand-rolled star/ring collectives, config/weight wire protocols, and
pipeline communicator (reference: src/nn/nn-network.cpp, nn-pipeline.cpp,
nn-topology.hpp) — with a `jax.sharding.Mesh` and XLA collectives over
ICI/DCN. Two execution styles:

* **GSPMD** (mesh.py + sharding.py): params/cache carry `NamedSharding`s, jit
  partitions the forward pass, XLA inserts all-reduces where the reference
  called `SYNC_NODE_SLICES` — the default and fastest path for TP(+DP).
* **Explicit shard_map** (pipeline.py): PPxTP with hand-placed `psum` (TP
  group) and `ppermute` (stage handoff) — the moral equivalent of the
  reference's topology-aware collectives, needed for pipeline parallelism
  where stages execute different weights.
"""

from .topology import PPxTPTopology
from .mesh import make_mesh
from .sharding import cache_shardings, data_shardings, param_shardings

__all__ = [
    "PPxTPTopology",
    "make_mesh",
    "param_shardings",
    "cache_shardings",
    "data_shardings",
]
