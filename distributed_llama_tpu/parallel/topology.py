"""PPxTP topology bookkeeping.

Pure-math mirror of the reference's `NnParallelTopology`
(reference: src/nn/nn-topology.hpp:15-55): global rank = ppRank * tpSize +
tpRank (row-major placement), TP group = the contiguous rank range of one
pipeline stage. On TPU "rank" is a mesh coordinate, but the mapping is kept
(and unit-tested) for parity with the reference's placement semantics and for
mapping reference-style CLI arguments onto mesh axes.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class PPxTPTopology:
    n_nodes: int
    pp_size: int

    def __post_init__(self):
        if self.pp_size < 1:
            raise ValueError("ppSize must be >= 1")
        if self.n_nodes < 1:
            raise ValueError("nNodes must be >= 1")
        if self.n_nodes % self.pp_size != 0:
            raise ValueError(
                f"nNodes ({self.n_nodes}) must be divisible by ppSize ({self.pp_size})"
            )

    @property
    def tp_size(self) -> int:
        return self.n_nodes // self.pp_size

    def pp_rank(self, rank: int) -> int:
        self._check(rank)
        return rank // self.tp_size

    def tp_rank(self, rank: int) -> int:
        self._check(rank)
        return rank % self.tp_size

    def rank(self, pp_rank: int, tp_rank: int) -> int:
        if not (0 <= pp_rank < self.pp_size and 0 <= tp_rank < self.tp_size):
            raise ValueError("pp/tp rank out of range")
        return pp_rank * self.tp_size + tp_rank

    def tp_group(self, rank: int) -> tuple[int, int]:
        """[start, end) rank range of this rank's TP group."""
        start = self.pp_rank(rank) * self.tp_size
        return start, start + self.tp_size

    def layer_range(self, pp_rank: int, n_layers: int) -> tuple[int, int]:
        """Contiguous layer range of a stage (reference: src/llm.cpp:210-216):
        floor split, the last stage absorbs the remainder."""
        per_stage = n_layers // self.pp_size
        start = pp_rank * per_stage
        end = n_layers if pp_rank == self.pp_size - 1 else start + per_stage
        return start, end

    def _check(self, rank: int):
        if not (0 <= rank < self.n_nodes):
            raise ValueError(f"rank {rank} out of range")
