"""Multi-host execution: process initialization and DCN-aware meshes.

The reference scales across hosts with its TCP full mesh + worker processes
(reference: NnNetwork::serve/connect, src/nn/nn-network.cpp:516-629; workers
run `dllama worker`). The TPU equivalent is JAX multi-controller SPMD: every
host runs the SAME program, `jax.distributed.initialize` wires the runtime
(coordinator address from env or args, like the reference's --workers list),
and `jax.devices()` becomes the global device set. There is no root/worker
asymmetry and no weight streaming — each process `device_put`s the shards its
local chips own.

Mesh placement policy (the scaling-book recipe): axes that carry per-token
collectives (tp, sp — all-reduce/softmax-combine every layer) must ride ICI
inside a slice; axes with rare or point-to-point transfers (pp stage handoff
once per step, dp never) may span the slower DCN between slices. That is the
same conclusion the reference reached empirically on slow Ethernet — TP
stops scaling at 4 nodes while PP=4 gives 21x (SURVEY.md §6) — promoted to a
placement rule.
"""

from __future__ import annotations

import os

import numpy as np
import jax
from jax.sharding import Mesh

from .mesh import AXES


def _distributed_client_active() -> bool:
    """Whether jax.distributed.initialize has already run — checked WITHOUT
    touching the local backend. (`jax.process_count()` would initialize the
    backend as a side effect, and on a real pod `jax.distributed.initialize`
    must run *before* any backend initialization or bring-up fails.)"""
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Initialize the multi-controller runtime (no-op if single-process or
    already initialized). Arguments default to the JAX_* env vars / TPU
    metadata, so on a TPU pod slice a bare call suffices.

    Must be called before anything initializes the local backend (first
    `jax.devices()` / array op) — same ordering contract as
    `jax.distributed.initialize` itself."""
    if _distributed_client_active():
        return  # already initialized
    kw = {}
    if coordinator_address:
        kw["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kw["num_processes"] = num_processes
    if process_id is not None:
        kw["process_id"] = process_id
    if kw or os.environ.get("JAX_COORDINATOR_ADDRESS"):
        try:
            jax.distributed.initialize(**kw)
        except RuntimeError as e:
            # keep the documented no-op contract even if the private
            # global_state probe above stops working in a future JAX
            if "already initialized" not in str(e).lower():
                raise
    else:
        # bare --distributed, no explicit flags: let jax's cluster
        # auto-detection have a shot (TPU pod metadata, GKE env vars live
        # inside initialize() itself, not in any env var this code could
        # check without initializing a backend). On plain TPU VM slices
        # jax.devices() is natively global, so falling back to
        # single-process is correct there; on an undetectable environment
        # the fallback keeps single-machine runs working.
        try:
            jax.distributed.initialize()
        except ValueError as e:
            # auto-detection found no usable cluster spec: fall back to
            # single-process (plain TPU VM slices are already global; a
            # single machine with --distributed just runs local). Runtime
            # failures on a DETECTED cluster propagate: silently running P
            # duplicate single-process jobs would be far worse than a loud
            # failure.
            import sys

            print(
                f"ℹ️  --distributed: no cluster detected ({e}); continuing "
                f"single-process (pass --coordinator/--num-processes/"
                f"--process-id on env-driven clusters)",
                file=sys.stderr,
            )
        except RuntimeError as e:
            if "already initialized" in str(e).lower():
                return
            # jax has reworded this error across versions ("... before any
            # JAX calls" vs "... before any JAX computations are executed");
            # match the stable prefix so the documented single-process
            # fallback keeps engaging on a live backend
            if "before any jax" in str(e).lower():
                # Something touched the backend before us. On a REAL cluster
                # (coordinator env vars present) falling back would run
                # every host as an independent single-process job — the
                # duplicate-job hazard — so that is a HARD error (ADVICE
                # r4). Without any cluster signal, bare --distributed on a
                # single machine (library/tests with a live backend) keeps
                # the documented single-process fallback.
                # explicit coordinator env counts as intent, and so does a
                # TPU worker list naming MORE THAN ONE host (a pod slice;
                # single-host TPU VMs carry their own name there, which is
                # why presence alone is not a signal)
                cluster_env = [
                    v
                    for v in (
                        "COORDINATOR_ADDRESS",
                        "MEGASCALE_COORDINATOR_ADDRESS",
                        "JAX_COORDINATOR_ADDRESS",
                    )
                    if os.environ.get(v)
                ]
                hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
                if len([h for h in hosts.split(",") if h.strip()]) > 1:
                    cluster_env.append("TPU_WORKER_HOSTNAMES(multi-host)")
                if cluster_env:
                    raise RuntimeError(
                        f"--distributed on a detected cluster ({cluster_env[0]} "
                        "is set) but the JAX backend was already initialized "
                        "before initialize_distributed(); call it before any "
                        "jax.devices()/array op — continuing would run every "
                        "host as an independent single-process job"
                    ) from e
                import sys

                print(
                    "ℹ️  --distributed: backend already initialized and no "
                    "cluster env detected; continuing single-process",
                    file=sys.stderr,
                )
                return
            raise


def make_multihost_mesh(
    tp: int = 0, pp: int = 1, dp: int = 1, sp: int = 1, ep: int = 1
) -> Mesh:
    """Global ("dp","pp","ep","tp","sp") mesh over all hosts' devices.

    tp=0 means "all remaining devices". Device order: JAX enumerates TPU
    devices so that consecutive devices share ICI; keeping ep/tp/sp innermost
    (fastest-varying) puts the per-layer collectives on ICI links, and
    pp/dp split across hosts/slices where only stage handoffs (ppermute)
    or nothing cross DCN.
    """
    devices = jax.devices()
    n = len(devices)
    if tp == 0:
        denom = pp * dp * sp * ep
        if n % denom:
            raise ValueError(f"{n} devices not divisible by pp*dp*sp*ep={denom}")
        tp = n // denom
    need = dp * pp * ep * tp * sp
    if need != n:
        raise ValueError(f"mesh {dp}x{pp}x{ep}x{tp}x{sp} != {n} global devices")
    arr = np.asarray(devices).reshape(dp, pp, ep, tp, sp)  # dlt: allow(host-sync) — array of device handles, no data transfer
    return Mesh(arr, AXES)
