"""Pipeline-parallel (PPxTP) forward via shard_map + ppermute.

The explicit-collectives twin of the GSPMD path. The reference implements PP
by giving each stage a contiguous layer range and shipping activations
stage-to-stage over TCP with a header/checksum protocol (reference:
src/nn/nn-pipeline.cpp:61-148, graph bridge src/llm.cpp:575-590). Here:

* the stacked layer axis of every per-layer weight is sharded over the mesh's
  `pp` axis — each device holds n_layers/pp layers (reference layer ranges,
  src/llm.cpp:210-216, with the divisibility requirement made explicit);
* activations hand off stage-to-stage with `lax.ppermute` over ICI — the
  whole NnPipelineCommunicator collapses into one collective;
* inside a stage, TP runs exactly like the reference's head-split: local
  heads/ff slices, `lax.psum` over the `tp` axis after the attention and FFN
  output projections (reference SYNC_NODE_SLICES, src/llm.cpp:418,569);
* logits are computed on the stage holding the final output and broadcast
  with a psum-mask (replacing the reference's root-only logits pipe).

Single-token decode necessarily serializes across stages (each round only
one stage does useful work — the same bubble the reference has per token).
Prefill gets the PP win via `microbatches`: the prompt is cut into pp
chunks that flow through stages back-to-back, keeping all stages busy
(the reference's prefill chunking heuristic, src/app.cpp:156-184, exists
for exactly this reason).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # newer jax exports shard_map at the top level
    from jax import shard_map as _shard_map
    if not callable(_shard_map):  # some versions expose a module by that name
        raise ImportError
except ImportError:  # older jax keeps it under experimental
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect as _inspect

if "check_vma" in _inspect.signature(_shard_map).parameters:
    shard_map = _shard_map
else:
    def shard_map(f=None, **kw):
        """Older-jax adapter: the replication-check kwarg was renamed
        check_rep -> check_vma when shard_map left experimental."""
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map(f, **kw) if f is not None else _shard_map(**kw)

from ..models.config import ModelConfig
from ..models.params import KVCache, ModelParams
from ..models.transformer import _layer, linear, rms_norm
from ..ops.rope import RopeTables


def pp_param_shardings(mesh: Mesh, moe: bool = False) -> dict:
    """param_shardings variant for the pipeline path: the stacked layer axis
    shards over `pp` in addition to the TP feature split."""

    def _ns(*spec):
        return NamedSharding(mesh, P(*spec))

    def entry(quant_pair, dense):
        return {"quant": quant_pair, "dense": dense}

    # packed T-layout quant pairs (ops/quant.py): q [L, nb*4, out] int32,
    # d [L, nb, out]
    row = entry((_ns("pp", None, "tp"), _ns("pp", None, "tp")), _ns("pp", "tp", None))
    col = entry((_ns("pp", "tp", None), _ns("pp", "tp", None)), _ns("pp", None, "tp"))
    # expert stacks [L, E, ...]: expert axis over `ep` (true expert
    # placement), ff axis over `tp` (the reference's TP-within-expert)
    erow = entry((_ns("pp", "ep", None, "tp"), _ns("pp", "ep", None, "tp")),
                 _ns("pp", "ep", "tp", None))
    ecol = entry((_ns("pp", "ep", "tp", None), _ns("pp", "ep", "tp", None)),
                 _ns("pp", "ep", None, "tp"))
    lrep = entry((_ns("pp"), _ns("pp")), _ns("pp"))  # per-layer vectors
    rep = entry((_ns(), _ns()), _ns())

    return {
        "q": row,
        "k": row,
        "v": row,
        # fused projections: row-split; fused out axis is per-shard
        # interleaved at load (models/params.py _fuse_rows)
        "wqkv": row,
        "w13": row,
        "wo": col,
        "w1": erow if moe else row,
        "w3": erow if moe else row,
        "w2": ecol if moe else col,
        "wcls": entry((_ns(None, "tp"), _ns(None, "tp")), _ns("tp", None)),
        "embedding": rep,
        "final_norm": rep,
        "norm0": lrep,
        "norm1": lrep,
        "q_norm": lrep,
        "k_norm": lrep,
        "moe_gate": lrep,
    }


def pp_cache_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("pp", "dp", "sp", "tp", None))


def pp_paged_pool_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the paged KV pool [L, n_pages, page_size, n_kv, head_dim]
    (runtime/paged_kv.py) on a pipeline mesh: the layer stack over `pp` and
    the kv heads over `tp` — exactly the axes `pp_cache_sharding` shards on
    the contiguous cache — with the page axis REPLICATED: page ids are
    global, so the host-side pool, tables, refcounts, and prefix-page
    sharing need zero mesh awareness (the mesh-paged design's whole
    point). Inside shard_map each stage sees [L/pp, n_pages, ps, h/tp, d]
    and indexes it with the same global page ids every other stage uses."""
    return NamedSharding(mesh, P("pp", None, None, "tp", None))


def pp_prefix_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a prefix-cache KV slice [L, P, heads, head_dim]
    (runtime/prefix_cache.py): the live cache's own per-stage layout minus
    the batch axis — layer stack over pp, kv heads over tp, the (short)
    cached seq axis replicated. A cached slice spliced into a row must land
    stage-for-stage where `pp_cache_sharding` keeps that row's KV, or the
    splice pays a cross-stage reshuffle on every hit (and the graph audit's
    sharding check fails)."""
    return NamedSharding(mesh, P("pp", None, "tp", None))


def _local_stage(
    cfg, rope, x, positions, pos_start, layers, k_cache, v_cache, sp_ctx,
    ep_axis=None, kv_len=None, stacked_cache=False, page_table=None,
    page_size=None,
):
    """Run this device's resident layers over x (a scan, like the global
    forward but over the local slice).

    `stacked_cache`: the local [L_local, b, S, ...] cache rides the scan's
    CARRY with in-place per-layer updates (models/transformer.py) instead of
    being re-stacked through xs/ys — the decode path, where the re-stack was
    the per-token floor. Weights still arrive as per-layer xs slices.

    `page_table` (mesh-paged, runtime/paged_kv.py): k/v are then the LOCAL
    shard of the page pool ([L/pp, n_pages, ps, h/tp, d]) riding the carry;
    the replicated table steers writes/reads exactly like the single-chip
    paged path — always stacked (the pool has no per-layer xs form)."""
    reduce_fn = lambda z: jax.lax.psum(z, "tp")

    if stacked_cache or page_table is not None:

        def body(carry, per_layer):
            x, k_c, v_c = carry
            lp, li = per_layer
            x, k_c, v_c = _layer(
                cfg, rope, x, positions, pos_start, lp, k_c, v_c,
                reduce_fn=reduce_fn, sp_ctx=sp_ctx, ep_axis=ep_axis,
                kv_len=kv_len, stacked_cache=True, cache_layer=li,
                page_table=page_table, page_size=page_size,
            )
            return (x, k_c, v_c), None

        lids = jnp.arange(k_cache.shape[0], dtype=jnp.int32)
        (x, new_k, new_v), _ = jax.lax.scan(
            body, (x, k_cache, v_cache), (layers, lids)
        )
        return x, new_k, new_v

    def body(carry, per_layer):
        x = carry
        lp, k_c, v_c = per_layer
        x, k_c, v_c = _layer(
            cfg, rope, x, positions, pos_start, lp, k_c, v_c,
            reduce_fn=reduce_fn, sp_ctx=sp_ctx, ep_axis=ep_axis, kv_len=kv_len,
        )
        return x, (k_c, v_c)

    x, (new_k, new_v) = jax.lax.scan(body, x, (layers, k_cache, v_cache))
    return x, new_k, new_v


_COMPILED: dict = {}


def pipeline_forward(
    cfg: ModelConfig,
    mesh: Mesh,
    params: ModelParams,
    rope: RopeTables,
    cache: KVCache,
    tokens: jnp.ndarray,  # [b, t]
    pos_start,  # scalar int32, or [b] for independent per-row sequences
    logits_mode: str = "last",
    microbatches: int = 1,
    kv_len: int | None = None,  # static GLOBAL KV read bound
    # (models.transformer._layer); under sp each shard clamps it to its
    # local slice — min(kv_len, local_seq) — which is exact (see _layer)
    page_table=None,  # mesh-paged KV (runtime/paged_kv.py): [b, slots]
    # int32, REPLICATED over the mesh (page ids are global); cache is then
    # the pp/tp-sharded page pool (pp_paged_pool_sharding)
    page_size: int | None = None,
):
    """PPxTP forward step. Same contract as models.transformer.forward.

    `microbatches` > 1 splits the batch's token axis into that many equal
    chunks pushed through the pipeline back-to-back (prefill). Must divide t.

    Partition specs must be read off the *concrete* input arrays (inside jit
    they are tracers without NamedShardings), so this wrapper builds the
    shard_map program once per (cfg, mesh, mode, specs) and caches the
    jitted function.
    """
    if jnp.shape(tokens)[-1] % max(microbatches, 1) != 0:
        raise ValueError(
            f"microbatches ({microbatches}) must divide the token length "
            f"({jnp.shape(tokens)[-1]})"
        )
    per_row = jnp.ndim(pos_start) > 0
    paged = page_table is not None
    fn = _cached_pipeline_fn(
        cfg, mesh, params, cache,
        ("fwd", logits_mode, microbatches, kv_len, per_row, paged, page_size),
        lambda ps, cs: _build_pipeline_fn(
            cfg, mesh, ps, cs, logits_mode, microbatches, kv_len,
            per_row=per_row, page_size=page_size if paged else None,
        ),
    )
    if paged:
        return fn(
            params, rope, cache, jnp.asarray(tokens),
            jnp.asarray(pos_start, jnp.int32), jnp.asarray(page_table),
        )
    return fn(params, rope, cache, jnp.asarray(tokens), jnp.asarray(pos_start, jnp.int32))


def _cached_pipeline_fn(cfg, mesh, params, cache, extra_key, builder):
    """Build-once cache for the jitted shard_map programs.

    Partition specs must be read off the *concrete* input arrays (inside jit
    they are tracers without NamedShardings), so the program is built once
    per (cfg, mesh, variant, specs) and cached. Pallas interpret mode rides
    in cfg (cfg.pallas_interpret), so it participates in the key — a program
    traced in one mode is never replayed in the other.
    """
    params_leaves, params_def = jax.tree.flatten(params)
    cache_leaves, cache_def = jax.tree.flatten(cache)
    key = (
        cfg,
        mesh,
        extra_key,
        tuple(_spec_of(a) for a in params_leaves),
        tuple(_spec_of(a) for a in cache_leaves),
    )
    fn = _COMPILED.get(key)
    if fn is None:
        params_spec = jax.tree.unflatten(params_def, [_spec_of(a) for a in params_leaves])
        cache_spec = jax.tree.unflatten(cache_def, [_spec_of(a) for a in cache_leaves])
        fn = builder(params_spec, cache_spec)
        _COMPILED[key] = fn
    return fn


def _mesh_ctx(mesh, k_cache):
    """(sp_ctx, ep_axis) for a shard_map body over this mesh."""
    sp_ctx = None
    if mesh.shape["sp"] > 1:
        local_seq = k_cache.shape[2]
        sp_ctx = ("sp", jax.lax.axis_index("sp") * local_seq)
    ep_axis = "ep" if mesh.shape.get("ep", 1) > 1 else None
    return sp_ctx, ep_axis


def _stage_rounds(
    cfg, pp, params, rope_t, x_all, k_cache, v_cache, pos_start, n_micro,
    sp_ctx, ep_axis, kv_len=None, page_table=None, page_size=None,
):
    """Push x_all [b, t, dim] through the GPipe schedule; returns
    (x_out [b, t, dim] — valid on every stage, k_cache, v_cache).

    Microbatch m enters stage 0 in round m; stage s processes it in round
    m+s; total rounds = n_micro + pp - 1. Each device carries one in-flight
    activation slot `x`.

    `pos_start` may be a scalar (all rows aligned — the single-sequence
    path) or a [b] vector (independent per-row sequences — batched serving
    on meshes). The vector path routes the cache writes through `_layer`'s
    OOB-drop scatter, so a row parked at pos seq_len writes nothing.
    """
    pp_rank = jax.lax.axis_index("pp")
    b, t, _ = x_all.shape
    mt = t // n_micro
    per_row = jnp.ndim(pos_start) > 0

    x = jnp.zeros((b, mt, cfg.dim), jnp.float32)
    done = []
    for r in range(n_micro + pp - 1):
        # inject microbatch r into stage 0's slot
        if r < n_micro:
            x_in = jax.lax.dynamic_slice_in_dim(x_all, r * mt, mt, axis=1)
            x = jnp.where(pp_rank == 0, x_in, x)
        mb_idx = r - pp_rank  # which microbatch this stage holds this round
        pos0 = pos_start + jnp.maximum(mb_idx, 0) * mt
        active = jnp.logical_and(mb_idx >= 0, mb_idx < n_micro)
        off = jnp.arange(mt, dtype=jnp.int32)
        if page_table is not None:
            # mesh-paged rounds (runtime/paged_kv.py): the local pool shard
            # updates IN PLACE inside the layer scan for ANY microbatch
            # size — an inactive stage parks at seq_len and its writes DROP
            # through the paged scatter, so the contiguous path's commit
            # window (and its whole read+select+write machinery) vanishes.
            # pos_eff stays scalar on the aligned prefill path so the flash
            # kernel's scalar-pos gate still sees it.
            pos_eff = jnp.where(active, pos0, jnp.int32(cfg.seq_len))
            positions = pos_eff[..., None] + off[None, :]
            positions = jnp.broadcast_to(positions, (b, mt))
            y, k_cache, v_cache = _local_stage(
                cfg, rope_t, x, positions, pos_eff, params.layers, k_cache,
                v_cache, sp_ctx, ep_axis=ep_axis, kv_len=kv_len,
                page_table=page_table, page_size=page_size,
            )
        elif mt == 1:
            # decode rounds: the local cache stack updates IN PLACE inside
            # the layer scan's carry (stacked_cache). An inactive stage is
            # "parked": its rows point at the global seq_len, so the
            # OOB-drop scatter writes nothing — replacing the old
            # read+select+write window commit AND the xs/ys re-stack of the
            # whole local allocation every round (the per-token floor).
            pos_eff = jnp.broadcast_to(
                jnp.where(active, pos0, jnp.int32(cfg.seq_len)), (b,)
            )
            positions = pos_eff[:, None] + off[None, :]
            y, k_cache, v_cache = _local_stage(
                cfg, rope_t, x, positions, pos_eff, params.layers, k_cache,
                v_cache, sp_ctx, ep_axis=ep_axis, kv_len=kv_len,
                stacked_cache=True,
            )
        else:
            positions = (pos0[:, None] + off[None, :]) if per_row else (pos0 + off[None, :])
            positions = jnp.broadcast_to(positions, (b, mt))

            y, k_upd, v_upd = _local_stage(
                cfg, rope_t, x, positions, pos0, params.layers, k_cache, v_cache,
                sp_ctx, ep_axis=ep_axis, kv_len=kv_len,
            )
            # commit cache only when this stage held a real microbatch.
            # Without sp, only rows [pos0, pos0+mt) can differ — select just
            # that window (a full-cache jnp.where would read+write the whole
            # allocation per round)
            if sp_ctx is None:
                if per_row:
                    # per-row windows: each row's [pos0_r, pos0_r+mt) slice
                    # may start anywhere, so vmap the window select over the
                    # batch axis (cache axis 1). A parked row's pos0 clamps
                    # into the tail here, but _layer's drop-scatter left
                    # upd == full for it, so the re-write is an identity.
                    def commit(full, upd):
                        def row(fr, ur, p):  # [L, S, h, d]
                            new_win = jax.lax.dynamic_slice_in_dim(ur, p, mt, axis=1)
                            old_win = jax.lax.dynamic_slice_in_dim(fr, p, mt, axis=1)
                            win = jnp.where(active, new_win, old_win)
                            return jax.lax.dynamic_update_slice_in_dim(fr, win, p, axis=1)

                        return jax.vmap(row, in_axes=(1, 1, 0), out_axes=1)(full, upd, pos0)

                else:

                    def commit(full, upd):
                        new_win = jax.lax.dynamic_slice_in_dim(upd, pos0, mt, axis=2)
                        old_win = jax.lax.dynamic_slice_in_dim(full, pos0, mt, axis=2)
                        win = jnp.where(active, new_win, old_win)
                        return jax.lax.dynamic_update_slice_in_dim(full, win, pos0, axis=2)

                k_cache = commit(k_cache, k_upd)
                v_cache = commit(v_cache, v_upd)
            else:
                # sp scatters rows anywhere in the local shard — no window bound
                k_cache = jnp.where(active, k_upd, k_cache)
                v_cache = jnp.where(active, v_upd, v_cache)
        # last stage's output for microbatch (r - pp + 1) is final
        if r >= pp - 1:
            done.append(jnp.where(pp_rank == pp - 1, y, 0.0))
        # hand off to the next stage (wraps; stage 0's incoming is
        # overwritten by the next injected microbatch)
        x = jax.lax.ppermute(y, "pp", [(i, (i + 1) % pp) for i in range(pp)])

    # final outputs, valid on the last stage; broadcast to all stages so
    # every device computes logits identically
    x_out = jnp.concatenate(done, axis=1)
    x_out = jax.lax.psum(x_out, "pp")
    return x_out, k_cache, v_cache


def _logits_of(cfg, params, x_out):
    """Final norm + sharded wcls + tp all-gather -> full logits, f32."""
    x_out = rms_norm(x_out, params.final_norm, cfg.norm_epsilon)
    logits_local = linear(
        x_out, params.wcls, cfg.dtype, cfg.pallas_arg, cfg.q80_activations
    )  # vocab/tp slice
    logits = jax.lax.all_gather(logits_local, "tp", axis=-1, tiled=True)
    return logits.astype(jnp.float32)


def _build_pipeline_fn(
    cfg, mesh, params_spec, cache_spec, logits_mode, microbatches, kv_len=None,
    per_row=False, page_size=None,
):
    pp = mesh.shape["pp"]
    rope_spec = RopeTables(cos=P(), sin=P())
    logits_spec = P("dp", None) if logits_mode == "last" else P("dp", None, None)
    paged = page_size is not None
    in_specs = (
        params_spec, rope_spec, cache_spec, P("dp", None),
        P("dp") if per_row else P(),
    )
    if paged:
        in_specs = in_specs + (P(None, None),)  # replicated page table

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(logits_spec, cache_spec),
        check_vma=False,
    )
    def run(params, rope_t, cache, tokens, pos_start, page_table=None):
        k_cache, v_cache = cache.k, cache.v  # [L_local, b_local, local_seq, kvh_local, hd]
        sp_ctx, ep_axis = _mesh_ctx(mesh, k_cache)
        x_all = params.embedding[tokens].astype(jnp.float32)  # [b_local, t, dim]
        x_out, k_cache, v_cache = _stage_rounds(
            cfg, pp, params, rope_t, x_all, k_cache, v_cache, pos_start,
            max(microbatches, 1), sp_ctx, ep_axis, kv_len=kv_len,
            page_table=page_table, page_size=page_size,
        )
        if logits_mode == "last":
            x_out = x_out[:, -1, :]
        return _logits_of(cfg, params, x_out), KVCache(k=k_cache, v=v_cache)

    return jax.jit(run, donate_argnums=(2,))


def pipeline_decode_chunk(
    cfg: ModelConfig,
    mesh: Mesh,
    params: ModelParams,
    rope: RopeTables,
    cache: KVCache,
    token: jnp.ndarray,  # [b] int32 — the token to feed first
    pos_start,  # scalar int32, or [b] for independent per-row sequences
    key: jnp.ndarray,
    n_steps: int = 16,
    temperature: float = 0.0,
    topp: float = 0.9,
    kv_len: int | None = None,  # static GLOBAL KV read bound covering
    # pos_start + n_steps; under sp each shard clamps to its local slice
    page_table=None,  # mesh-paged KV: replicated [b, slots] table
    page_size: int | None = None,
):
    """On-device chunked decode for pipeline meshes: the same
    K-forwards-per-host-call loop as runtime/decode.py decode_chunk, but with
    each forward crossing the pp stages via ppermute inside the scan — no
    per-token host round trip on PP/SP/EP meshes.

    Returns (tokens [b, n_steps], last_token [b], cache) — `last_token`
    aliases tokens[:, -1] on device (see runtime/decode.decode_chunk).
    """
    per_row = jnp.ndim(pos_start) > 0
    paged = page_table is not None
    fn = _cached_pipeline_fn(
        cfg, mesh, params, cache,
        ("decode", n_steps, temperature, topp, kv_len, per_row, paged, page_size),
        lambda ps, cs: _build_pipeline_decode_fn(
            cfg, mesh, ps, cs, n_steps, temperature, topp, kv_len,
            per_row=per_row, page_size=page_size if paged else None,
        ),
    )
    if paged:
        return fn(
            params, rope, cache, jnp.asarray(token),
            jnp.asarray(pos_start, jnp.int32), key, jnp.asarray(page_table),
        )
    return fn(
        params, rope, cache, jnp.asarray(token),
        jnp.asarray(pos_start, jnp.int32), key,
    )


def _build_pipeline_decode_fn(
    cfg, mesh, params_spec, cache_spec, n_steps, temperature, topp, kv_len=None,
    per_row=False, page_size=None,
):
    from ..ops.sampling import sample_logits

    pp = mesh.shape["pp"]
    rope_spec = RopeTables(cos=P(), sin=P())
    paged = page_size is not None
    in_specs = (
        params_spec, rope_spec, cache_spec, P("dp"),
        P("dp") if per_row else P(), P(),
    )
    if paged:
        in_specs = in_specs + (P(None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P("dp", None), P("dp"), cache_spec),
        check_vma=False,
    )
    def run(params, rope_t, cache, token, pos_start, key, page_table=None):
        sp_ctx, ep_axis = _mesh_ctx(mesh, cache.k)
        # independent sampling randomness per dp shard (the key arrives
        # replicated; without the fold every shard would draw the same coins
        # for its local batch rows)
        if mesh.shape["dp"] > 1:
            key = jax.random.fold_in(key, jax.lax.axis_index("dp"))

        def step(carry, _):
            token, pos, k_cache, v_cache, key = carry
            x = params.embedding[token[:, None]].astype(jnp.float32)
            x_out, k_cache, v_cache = _stage_rounds(
                cfg, pp, params, rope_t, x, k_cache, v_cache, pos, 1, sp_ctx,
                ep_axis, kv_len=kv_len, page_table=page_table,
                page_size=page_size,
            )
            logits = _logits_of(cfg, params, x_out[:, -1, :])
            key, sub = jax.random.split(key)
            nxt = sample_logits(logits, sub, temperature, topp)
            return (nxt, pos + 1, k_cache, v_cache, key), nxt

        (last, _, k_cache, v_cache, _), toks = jax.lax.scan(
            step,
            (token, jnp.asarray(pos_start, jnp.int32), cache.k, cache.v, key),
            None,
            length=n_steps,
        )
        return jnp.transpose(toks, (1, 0)), last, KVCache(k=k_cache, v=v_cache)

    return jax.jit(run, donate_argnums=(2,))


def pipeline_batch_decode_chunk(
    cfg: ModelConfig,
    mesh: Mesh,
    params: ModelParams,
    rope: RopeTables,
    cache: KVCache,
    token: jnp.ndarray,  # [b] int32
    pos: jnp.ndarray,  # [b] int32 per-row positions (seq_len = parked)
    keys: jnp.ndarray,  # [b, 2] uint32 per-row threefry key states
    temperature: jnp.ndarray,  # [b] f32
    topp: jnp.ndarray,  # [b] f32
    n_steps: int = 16,
    kv_len: int | None = None,
    page_table=None,  # mesh-paged KV: replicated [b, slots] table
    page_size: int | None = None,
):
    """Mesh twin of runtime/batch_session.batch_decode_chunk: everything
    per-row and traced (continuous batching on tp/pp/sp/ep meshes). Returns
    (tokens [b, n_steps], cache, keys)."""
    paged = page_table is not None
    fn = _cached_pipeline_fn(
        cfg, mesh, params, cache, ("batch_decode", n_steps, kv_len, paged, page_size),
        lambda ps, cs: _build_pipeline_batch_decode_fn(
            cfg, mesh, ps, cs, n_steps, kv_len,
            page_size=page_size if paged else None,
        ),
    )
    args = (
        params, rope, cache, jnp.asarray(token), jnp.asarray(pos, jnp.int32),
        jnp.asarray(keys), jnp.asarray(temperature, jnp.float32),
        jnp.asarray(topp, jnp.float32),
    )
    if paged:
        return fn(*args, jnp.asarray(page_table))
    return fn(*args)


def _build_pipeline_batch_decode_fn(
    cfg, mesh, params_spec, cache_spec, n_steps, kv_len, page_size=None
):
    from ..ops.sampling import sample_logits_per_row, split_row_keys

    pp = mesh.shape["pp"]
    rope_spec = RopeTables(cos=P(), sin=P())
    paged = page_size is not None
    in_specs = (
        params_spec, rope_spec, cache_spec, P("dp"), P("dp"),
        P("dp", None), P("dp"), P("dp"),
    )
    if paged:
        in_specs = in_specs + (P(None, None),)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P("dp", None), cache_spec, P("dp", None)),
        check_vma=False,
    )
    def run(params, rope_t, cache, token, pos0, keys, temperature, topp,
            page_table=None):
        sp_ctx, ep_axis = _mesh_ctx(mesh, cache.k)

        def step(carry, _):
            token, pos, k_cache, v_cache, keys = carry
            x = params.embedding[token[:, None]].astype(jnp.float32)
            x_out, k_cache, v_cache = _stage_rounds(
                cfg, pp, params, rope_t, x, k_cache, v_cache, pos, 1, sp_ctx,
                ep_axis, kv_len=kv_len, page_table=page_table,
                page_size=page_size,
            )
            logits = _logits_of(cfg, params, x_out[:, -1, :])
            keys, subs = split_row_keys(keys)
            nxt = sample_logits_per_row(logits, subs, temperature, topp)
            return (nxt, pos + 1, k_cache, v_cache, keys), nxt

        (_, _, k_cache, v_cache, keys), toks = jax.lax.scan(
            step, (token, pos0, cache.k, cache.v, keys), None, length=n_steps
        )
        return jnp.transpose(toks, (1, 0)), KVCache(k=k_cache, v=v_cache), keys

    return jax.jit(run, donate_argnums=(2,))


def _spec_of(a) -> P:
    sh = getattr(a, "sharding", None)
    if isinstance(sh, NamedSharding):
        # normalize trailing Nones away: plain-jit programs (the paged
        # pool's page_copy/gather/scatter) return shardings with the
        # trailing unsharded dims TRIMMED, and an un-normalized spec here
        # would give the post-warmup cache a different _cached_pipeline_fn
        # key than warmup compiled — a guaranteed recompile-sentinel breach
        spec = tuple(sh.spec)
        while spec and spec[-1] is None:
            spec = spec[:-1]
        return P(*spec)
    return P()
