"""NamedSharding rules for params, cache, and data.

The placement math mirrors the reference's slicers exactly
(reference: src/nn/nn-core.cpp:222-324):

  q/k/v, w1, w3   row-split over TP (output-feature axis)  -> sliceRowMatmul
  wo, w2          col-split over TP (input-feature axis)   -> sliceColMatmul
  wcls            row-split over vocab                     -> sliceRowMatmul
  kv cache        head axis over TP                        -> sliceKvCache
  moe experts     ff axis over TP (TP-within-expert, the reference's MoE
                  layout: every node holds a slice of every expert,
                  src/llm.cpp:682-684); expert axis over an `ep` upgrade is
                  planned (parallel/pipeline.py docstring)
  norms, gate,    replicated                               -> loadAll
  embedding

With these in place, jit/GSPMD inserts exactly the collectives the reference
hand-codes: an all-reduce over the TP group after the attention and FFN
output projections and after logits (reference: SYNC_NODE_SLICES at
src/llm.cpp:418,569,633).

Q40 weights are (q, d) component pairs in the T layout (ops/quant.py):
q: [L, in/8, out] int32 packed words, d: [L, in/32, out]. The out axis is the LAST axis
(row-split shards it); the in axis is the blocks axis at index 1 (col-split
shards it). Dense weights remain logical [L, out, in].

Constraint carried over from the reference (src/app.cpp:341-343):
tp must divide n_kv_heads (and the per-32-block count for col-splits).
"""

from __future__ import annotations

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _ns(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def param_shardings(mesh: Mesh, moe: bool = False) -> dict:
    """Role -> sharding (or (q, d) pair of shardings for Q40 roles).

    Works for both dense and Q40 weights: loaders pick the pair form when the
    tensor is quantized. Layer axis (leading) is replicated — pipeline
    parallelism shards it explicitly in parallel/pipeline.py instead.
    """
    def entry(quant_pair, dense):
        return {"quant": quant_pair, "dense": dense}

    # Quant weights use the packed T layout (ops/quant.py): q [L, nb*4, out]
    # int32 words, d [L, nb, out]; dense weights stay [L, out, in].
    # row-split = shard the out axis (q/d last axis; dense axis 1)
    row = entry((_ns(mesh, None, None, "tp"), _ns(mesh, None, None, "tp")),
                _ns(mesh, None, "tp", None))
    # col-split = shard the in axis (q word-rows axis — block-aligned for any
    # tp dividing nb, since each block owns 4 contiguous word rows; d blocks
    # axis; dense axis 2)
    col = entry((_ns(mesh, None, "tp", None), _ns(mesh, None, "tp", None)),
                _ns(mesh, None, None, "tp"))
    # MoE expert stacks: [L, E, ...] — ff axis sharded (TP-within-expert)
    erow = entry((_ns(mesh, None, None, None, "tp"), _ns(mesh, None, None, None, "tp")),
                 _ns(mesh, None, None, "tp", None))
    ecol = entry((_ns(mesh, None, None, "tp", None), _ns(mesh, None, None, "tp", None)),
                 _ns(mesh, None, None, None, "tp"))
    rep = entry((_ns(mesh), _ns(mesh)), _ns(mesh))

    return {
        "q": row,
        "k": row,
        "v": row,
        # fused projections (models/params.py _fuse_rows): plain row-split —
        # the fused out axis is per-shard interleaved at load time
        "wqkv": row,
        "w13": row,
        "wo": col,
        "w1": erow if moe else row,
        "w3": erow if moe else row,
        "w2": ecol if moe else col,
        # wcls row-split over vocab: quant q [nb*4, vocab] / d [nb, vocab];
        # dense [vocab, dim]
        "wcls": entry((_ns(mesh, None, "tp"), _ns(mesh, None, "tp")), _ns(mesh, "tp", None)),
        "embedding": rep,
        "final_norm": rep,
        "norm0": rep,
        "norm1": rep,
        "q_norm": rep,
        "k_norm": rep,
        "moe_gate": rep,
    }


def cache_shardings(mesh: Mesh) -> NamedSharding:
    """KV cache [L, batch, seq, n_kv_heads, head_dim]: batch over dp, heads
    over tp, seq over sp (long-context)."""
    return _ns(mesh, None, "dp", "sp", "tp", None)


def data_shardings(mesh: Mesh) -> NamedSharding:
    """Token/position arrays [batch, t]: batch over dp."""
    return _ns(mesh, "dp", None)
